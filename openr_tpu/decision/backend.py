"""Decision compute backends: scalar (host) and TPU (batched kernels).

The backend seam is exactly the reference's pure-compute boundary
(SpfSolver takes LinkState/PrefixState in, RouteDb out, SpfSolver.h:136).
`ScalarBackend` wraps the oracle SpfSolver.  `TpuBackend` runs the
``multi_area_spf_tables`` + ``multi_area_select_from_tables`` kernels —
per-area SPF as a batch dim (Decision.cpp:762-773), global best-route
selection, per-area ECMP lane sets — and decodes device outputs back into
RibUnicastEntries with the cross-area min-metric merge
(SpfSolver.cpp:276-302) done during lane decode.  KSP2_ED_ECMP prefixes
run their masked re-solve fan-out as a second batched device call per
area (decision/ksp2.py) with only the greedy path trace + label-stack
assembly on the host.  Static routes and MPLS label routes stay scalar
(O(nodes), no per-prefix fan-out).  Both backends must produce identical
RouteDbs — enforced by differential tests.

Incremental rebuilds (Decision.cpp:908-952 parity): when Decision passes
``changed_prefixes`` (prefix-only delta, no topology/static/policy
change), both backends patch their previous RouteDb instead of a full
rebuild — the TPU path reuses device-resident SPF tables and runs the
selection kernel over ONLY the changed candidate rows (gathered to a
bucketed [K, C] batch), the scalar path re-runs createRouteForPrefix for
the changed set.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.decision.link_state import INF, LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, RibUnicastEntry
from openr_tpu.decision.spf_solver import (
    SpfSolver,
    drained_entry,
)
from openr_tpu.types import (
    NextHop,
    PrefixForwardingAlgorithm,
    RouteComputationRules,
    prefix_is_v4,
)

#: max-out-degree lane buckets: D is a static jit arg, so it must not
#: track raw topology churn or every new degree recompiles the kernel
DEGREE_BUCKETS = (4, 8, 16, 32, 64, 128, 256, 512, 1024)

#: gathered-changed-row buckets for the incremental selection batch
ROWSEL_BUCKETS = (64, 256, 1024, 4096, 16384, 65536, 262144)

#: sub-edge buckets for the bounded warm-repair kernel (the perturbed
#: frontier's in-edge count, padded so the jit cache stays stable)
SUB_EDGE_BUCKETS = (1024, 8192, 65536, 524288)

#: in-flight dispatch slots per chip in the streamed double-buffer
#: loops: shard N+1's pad/transfer overlaps shard N's solve, but no
#: chip ever queues more than this many undrained dispatches — the
#: DevicePool in-flight ledger enforces it per chip, so a committed
#: dispatch never waits on an UNRELATED chip's backlog
STREAM_SLOTS = 2

#: delta-fetch cutover: when more than this fraction of a shard's rows
#: changed, the compacted gather stops paying for itself (two fetch
#: rounds + gather dispatch vs one full fetch) — fetch the full shard
DELTA_FETCH_MAX_FRACTION = 0.5


def measure_dispatch_rt_ms() -> float:
    """Median device dispatch round trip (ms): one tiny op, blocked.
    ~75ms over a tunneled chip, ~0.1ms collocated — the number every
    auto device-vs-host cutover in this package calibrates against."""
    import time

    import jax.numpy as jnp

    (jnp.zeros(4) + 1).block_until_ready()  # compile warm-up
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()  # orlint: disable=clock-now,wallclock-reachability (host-latency calibration probe measuring REAL dispatch cost; steers engine choice, never emitted bytes)
        (jnp.zeros(4) + 1).block_until_ready()
        samples.append(time.perf_counter() - t0)  # orlint: disable=clock-now,wallclock-reachability (host-latency calibration probe measuring REAL dispatch cost; steers engine choice, never emitted bytes)
    samples.sort()
    return samples[1] * 1000.0


def estimate_scalar_work_items(area_link_states, prefix_state) -> int:
    """Work items (prefix rows + directed edges) for the auto cutovers'
    scalar-cost estimate — ONE formula shared by the backend's device
    cutover and Decision's what-if engine choice."""
    return len(prefix_state.prefixes()) + 2 * sum(
        ls.num_links() for ls in area_link_states.values()
    )


def _patch_route_db(
    prev_db: DecisionRouteDb,
    results: Dict[str, Optional[RibUnicastEntry]],
    static_routes: Dict[str, RibUnicastEntry],
) -> DecisionRouteDb:
    """Previous RouteDb + per-changed-prefix results → new RouteDb.
    A None result falls back to the static overlay (full-build rule:
    static routes fill prefixes the prefix states didn't produce,
    SpfSolver.cpp:343-349), else the route is deleted."""
    db = DecisionRouteDb(
        unicast_routes=dict(prev_db.unicast_routes),
        mpls_routes=dict(prev_db.mpls_routes),
    )
    for prefix, entry in results.items():
        if entry is None:
            entry = static_routes.get(prefix)
        if entry is None:
            db.unicast_routes.pop(prefix, None)
        else:
            db.unicast_routes[prefix] = entry
    return db


class DecisionBackend:
    def build_route_db(
        self,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
        changed_prefixes: Optional[Set[str]] = None,
        force_full: bool = False,
        cache_result: bool = True,
        warm_delta: bool = False,
        structural_delta: bool = False,
    ) -> Optional[DecisionRouteDb]:
        """``changed_prefixes`` is the EXACT prefix-churn delta since the
        previous call (None = unknown → full re-read of PrefixState).  The
        backend may patch its previous result only when a delta is given,
        ``force_full`` is False, and its own caches are intact (topology
        unchanged).  ``force_full`` demands full recomputation (first
        build, static-route or policy change) while still letting the
        backend use the delta for internal table maintenance.
        ``cache_result=False`` signals the caller will mutate the returned
        db (RibPolicy) — the backend must not keep it as an incremental
        base.  ``warm_delta`` is Decision's perturbation classification
        of THIS tick's topology churn: True means every pending topology
        change was a link weight/up-down or drain flip (no node or area
        entered/left the LSDB) and nothing else forced the full build —
        a warm-capable backend may then rebuild its device state
        incrementally from the previous generation, PROVIDED the result
        is identical to a cold full build.  The hint is advisory; the
        backend re-verifies structural compatibility against its own
        caches before trusting it.  ``structural_delta`` is the
        membership-churn classification (a node or area entered/left
        the LSDB and nothing else forced the build): a slot-capable
        backend may then patch its encoding in place (tombstones +
        free-list) and seed the warm kernels from the surviving region;
        declines fall back to a cold re-encode with a counted reason.
        The two hints are mutually exclusive."""
        raise NotImplementedError

    def counter_snapshot(self) -> Dict[str, float]:
        """Gauges for the Monitor's provider sweep (ctrl getCounters /
        `breeze monitor counters decision.backend.`)."""
        return {}

    def take_full_replace(self) -> bool:
        """True exactly once after a build whose result must be diffed
        against the WHOLE previous RouteDb even on an incremental tick.
        The quarantine swap is the one producer: when shadow
        verification replaces corrupt device output with the scalar
        oracle's, every entry programmed since the last verified sample
        is suspect and a changed-prefix-only diff would leave stale
        corrupt routes in the FIB."""
        return False

    def take_last_changed_prefixes(self) -> Optional[Set[str]]:
        """One-shot: the exact prefix set the LAST build could have
        changed, when the backend produced that build by PATCHING its
        previous RouteDb (warm-selective generation-delta rebuild) —
        every other prefix is object-identical to the previous
        generation's entry, so the caller may diff O(changed) instead of
        O(total) even on a topology tick.  None = no such guarantee
        (full rebuild, scalar path): diff everything."""
        return None


class ScalarBackend(DecisionBackend):
    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver
        self._last_db: Optional[DecisionRouteDb] = None

    def build_route_db(
        self,
        area_link_states,
        prefix_state,
        changed_prefixes=None,
        force_full=False,
        cache_result=True,
        warm_delta=False,
        structural_delta=False,
    ):
        if (
            changed_prefixes is not None
            and not force_full
            and self._last_db is not None
        ):
            if not any(
                ls.has_node(self.solver.my_node_name)
                for ls in area_link_states.values()
            ):
                self._last_db = None
                return None
            results = {
                p: self.solver.create_route_for_prefix(
                    p, area_link_states, prefix_state
                )
                for p in changed_prefixes
            }
            db = _patch_route_db(
                self._last_db, results, self.solver.get_static_routes()
            )
        else:
            db = self.solver.build_route_db(area_link_states, prefix_state)
        self._last_db = db if cache_result else None
        return db

    def counter_snapshot(self) -> Dict[str, float]:
        return {"decision.backend.device": 0.0}


class TpuBackend(DecisionBackend):
    """Device-accelerated buildRouteDb.

    Topology and candidate tables are padded to buckets so the jit cache
    stays warm across LSDB churn (SURVEY §7 hard-part 4).
    """

    #: assumed scalar build cost per work item (prefix row or directed
    #: edge) for the auto cutover — Python route computation measures
    #: ~10-25us/route across DecisionBenchmark scales; the estimate only
    #: needs to be right within ~2x to pick the right side of a ~100x
    #: crossover
    SCALAR_US_PER_ITEM = 10.0
    #: device build cost in dispatch round trips (encode + SPF + select
    #: + one bulk fetch)
    DEVICE_OVERHEAD_TRIPS = 2.5

    def __init__(
        self,
        solver: SpfSolver,
        node_buckets=(16, 64, 256, 1024, 4096, 16384),
        cand_buckets=(1, 2, 4, 8, 16, 32, 64),
        min_device_prefixes: Optional[int] = 0,
        clock=None,
        counters=None,
        tracer=None,
        resilience=None,
        parallel=None,
        probe=None,
        warm_rebuild: bool = True,
        plan_cache_entries: int = 0,
    ) -> None:
        self.solver = solver  # scalar fallback + MPLS/static
        if plan_cache_entries:
            # bound the content-hash RepairPlan memo (ops.repair) the
            # what-if/sweep planners ride; 0 keeps the library default
            from openr_tpu.ops.repair import set_plan_cache_cap

            set_plan_cache_cap(plan_cache_entries)
        # AOT-equivalence with the reference's compiled binary: persist
        # XLA executables so only the FIRST boot on a machine pays kernel
        # compilation (~14s of cold boot at 4096-node scale)
        from openr_tpu.ops.platform_env import enable_persistent_compile_cache

        enable_persistent_compile_cache()
        self.node_buckets = tuple(node_buckets)
        self.cand_buckets = tuple(cand_buckets)
        #: device-vs-scalar cutover.  None = AUTO-CALIBRATE: measure the
        #: dispatch round trip once at first build (~75ms over a
        #: tunneled chip, ~1ms locally) and choose scalar when the
        #: estimated scalar cost cannot amortize it — the DAEMON default
        #: (config.TpuComputeConfig), so small deployments never need to
        #: know the knob exists (VERDICT r3 weak #4).  0 (library
        #: default: deterministic for embedders/tests) = always device;
        #: N = manual prefix threshold.
        self.min_device_prefixes = min_device_prefixes
        #: measured dispatch round trip (ms); None until first probe
        self.auto_dispatch_rt_ms: Optional[float] = None
        self.num_small_scalar_builds = 0
        self.num_device_builds = 0
        self.num_scalar_builds = 0
        self.num_incremental_builds = 0
        #: scalar fallbacks caused specifically by a prefix advertised by
        #: more candidates than the largest candidate bucket (VERDICT r1
        #: weak #8: the cause must be distinguishable)
        self.num_fallback_cand_overflow = 0
        #: device-outage latch: while set, every build routes through the
        #: scalar oracle.  With a governor (the default) the ONLY writers
        #: are the BackendHealthGovernor, chaos, and this class — the
        #: orlint `resilience-latch` rule enforces that statically
        self.device_failed = False
        self.num_fallback_injected = 0
        self.num_dispatch_errors = 0
        #: chaos tpu_corrupt: perturb fetched kernel outputs WITHOUT
        #: raising — the silent-data-corruption model the governor's
        #: shadow verification exists to catch.  `_sdc_inject` corrupts
        #: every shard; `_sdc_devices` corrupts only the shards computed
        #: on the listed pool devices (per-chip SDC)
        self._sdc_inject = False
        self._sdc_devices: Set[int] = set()
        #: multi-chip dispatch knobs (config.ParallelConfig); the pool
        #: itself is built lazily on first use so embedders that never
        #: build routes never pay jax platform initialization
        self._parallel_enabled = parallel.enabled if parallel else True
        self._max_devices = parallel.max_devices if parallel else 0
        self._min_shard_rows = (
            parallel.min_shard_rows if parallel else 128
        )
        self._pool = None
        #: pipeline attribution (openr_tpu.tracing.pipeline): every
        #: stage of a device build records a phase-scoped span +
        #: `pipeline.{phase}.ms` histogram sample, and committed
        #: per-shard dispatches charge per-chip busy time.  Built from
        #: the injected clock/counters/tracer when not supplied;
        #: embedders without a clock get the shared disabled probe.
        if probe is None:
            from openr_tpu.tracing.pipeline import (
                PipelineProbe,
                disabled_probe,
            )

            probe = (
                PipelineProbe(clock, counters, tracer)
                if clock is not None
                else disabled_probe()
            )
        self.probe = probe
        #: per-device replicas of the device-resident SPF tables, keyed
        #: by device index and invalidated by table identity
        self._spf_replicas: dict = {}
        #: pool health generation the replica cache was built under —
        #: a quarantine/restore re-packs shard ownership, and replicas
        #: pinned to unhealthy chips are dropped at the next dispatch
        self._replica_health_seq = -1
        #: attribution of the LAST device build's freshly-computed rows:
        #: either a contiguous shard plan [(device, row_lo, row_hi)]
        #: (full builds) or an explicit row->device map (incremental
        #: gathers); the governor reads it to pin a shadow-verification
        #: mismatch on the one chip that produced the wrong rows
        self._attr_plan = None
        self._attr_rows = None
        self._attr_table = None
        #: health authority (openr_tpu/resilience/governor.py): shadow
        #: verification + circuit breaker + probed recovery.  `resilience`
        #: is a config.ResilienceConfig (None = defaults; enabled=False
        #: = legacy one-way latch, no governor)
        from openr_tpu.resilience.governor import BackendHealthGovernor

        self.governor = None
        if resilience is None or resilience.enabled:
            gov_kwargs = (
                {}
                if resilience is None
                else dict(
                    shadow_sample_every=resilience.shadow_sample_every,
                    failure_threshold=resilience.failure_threshold,
                    probe_backoff_initial_s=resilience.probe_backoff_initial_s,
                    probe_backoff_max_s=resilience.probe_backoff_max_s,
                    jitter_pct=resilience.jitter_pct,
                    seed=resilience.seed,
                    per_device=getattr(resilience, "per_device", True),
                )
            )
            self.governor = BackendHealthGovernor(
                self,
                clock=clock,
                counters=counters,
                tracer=tracer,
                **gov_kwargs,
            )
        #: EncodedMultiArea cache keyed by ((area, topology_seq), ...):
        #: most rebuilds are prefix churn on an unchanged graph, and
        #: re-encoding a 4096-node LSDB costs tens of ms of the debounce
        #: budget (SURVEY §7 hard-part 4)
        self._enc_cache: dict = {}
        #: Ksp2DeviceEngine per (area, topology_seq) — the traced-path memo
        #: itself lives in the LinkState; this only avoids rebuilding the
        #: link-id table every rebuild
        self._ksp2_engines: dict = {}
        self.num_encode_hits = 0
        self.num_encodes = 0
        #: device-resident per-area SPF tables, valid while (_spf_enc is
        #: the live encoding object, _spf_degree == D) — identity is held
        #: by reference, never by id(), to survive GC id reuse
        self._spf_tables = None
        self._spf_enc = None
        self._spf_degree = None
        #: warm-start generation-delta rebuild (the ISSUE-9 tentpole):
        #: the previous generation's SPF tables stay device-resident
        #: (plus small host mirrors for delta planning), and a
        #: warm-eligible topology tick re-relaxes only the perturbed
        #: frontier instead of re-running the cold hop-diameter solve.
        #: The context is PURGED — and the next device build forced
        #: through shadow verification — on anything that makes it
        #: suspect: corruption injection, a quarantine re-pack, the
        #: full-replace swap, or a structural/shape change.
        self._warm_enabled = bool(warm_rebuild)
        self._warm_ctx = None  # dict(enc, dist, nh, degree, tables)
        self._warm_changed_nodes = None  # [A, V] bool vs previous gen
        self._warm_base_enc = None  # ctx enc the last warm solve diffed
        self._warm_solved = False  # this build's tables came in warm
        self._warm_rounds = None  # (rounds_d, rounds_l) device scalars
        self._last_changed_prefixes: Optional[Set[str]] = None
        self.num_warm_builds = 0
        self.num_warm_subgraph_builds = 0
        self.num_warm_selective_builds = 0
        self.num_warm_cold_fallbacks = 0
        self.num_warm_purges = 0
        self.num_encode_patches = 0
        self.warm_last_est_depth = 0
        self.warm_last_reset_nodes = 0
        self.warm_last_rounds = (0, 0)
        self._warm_purge_reasons: Dict[str, int] = {}
        self._warm_fallback_reasons: Dict[str, int] = {}
        #: warm telemetry split by delta class (ISSUE 12): a rolling
        #: fleet upgrade lives on the STRUCTURAL ratio; drowning it in
        #: the (much more frequent) perturbation ticks would hide a
        #: cold-wall regression from the operator
        self._warm_class_builds: Dict[str, int] = {
            "perturbation": 0,
            "structural": 0,
        }
        self._warm_class_fallbacks: Dict[str, int] = {
            "perturbation": 0,
            "structural": 0,
        }
        self._warm_class_fallback_reasons: Dict[str, Dict[str, int]] = {
            "perturbation": {},
            "structural": {},
        }
        #: slot-stable encode telemetry: structural-membership patches
        #: applied in place vs declined-to-cold (with the reason)
        self.num_encode_slot_patches = 0
        self._slot_decline_reasons: Dict[str, int] = {}
        #: encode kind of the live encoding ("cold"/"patch"/"slot")
        self._last_encode_kind = "cold"
        #: KSP2 prefixes seen by the most recent decodes: their routes
        #: depend on the WHOLE topology (k-shortest re-solves), so the
        #: warm-selective patch path declines while any are present
        self._ksp2_present = False
        if self.governor is not None:
            # any quarantine transition (whole-backend or per-chip)
            # re-packs shard ownership and makes device residency
            # suspect — purge the warm context so the next generation
            # rebuilds cold and scalar-verified
            self.governor.add_quarantine_listener(
                lambda info: self._purge_warm(
                    f"quarantine:{info.get('reason', '')}"
                )
            )
        #: incremental candidate table (persistent across rebuilds);
        #: _table_synced guards against missed deltas when a build falls
        #: back to the scalar path (the table skips that tick's churn)
        from openr_tpu.decision.cand_table import CandidateTable

        self._cand_table = CandidateTable(cand_buckets=self.cand_buckets)
        self._table_synced = False
        #: previous device-built RouteDb + the enc it was built against
        self._last_db: Optional[DecisionRouteDb] = None
        self._last_enc = None
        #: one-shot: set when a quarantine swap makes the whole previous
        #: RouteDb suspect (see DecisionBackend.take_full_replace)
        self._full_replace = False
        #: on-device generation-delta context for COLD/full rebuilds
        #: (the warm-start take_last_changed_prefixes pattern extended
        #: to the full-build path): the previous full build's selection
        #: outputs stay device-resident per shard, the next full build
        #: runs the fused select+diff kernel, and only changed rows
        #: cross the host boundary.  Purged with the warm context on any
        #: suspicion event, and dropped whenever a build is not a
        #: full-table build (incremental/warm-selective patches make the
        #: resident outputs stale for their rows).
        self._prev_sel = None
        #: probe chip of the most recent full-dispatch plan (a failed
        #: probe shard must not mid-stream re-pack — the whole build
        #: falls back so the governor scores the probe)
        self._plan_probe = None
        #: per-shard device outputs of the stream in progress (set by
        #: `_stream_row_shards` on clean completion, consumed by
        #: `_retain_prev_sel`)
        self._stream_outs = None
        #: test seams for the streamed dispatcher: `_stream_pick`
        #: overrides completion-order selection (fn(pending) -> index)
        #: so reassembly is provably order-independent;  `_stream_fault`
        #: (fn(device_index), called inside the drain's try block)
        #: injects a mid-stream chip failure — both None in production
        self._stream_pick = None
        self._stream_fault = None
        self.num_stream_builds = 0
        self.num_stream_repacks = 0
        self.num_delta_builds = 0
        self.num_delta_rows_fetched = 0
        self.num_delta_rows_skipped = 0

    def build_route_db(
        self,
        area_link_states,
        prefix_state,
        changed_prefixes=None,
        force_full=False,
        cache_result=True,
        warm_delta=False,
        structural_delta=False,
    ):
        gov = self.governor
        probe = False
        self._last_changed_prefixes = None
        if gov is not None:
            from openr_tpu.resilience.governor import (
                ADMIT_PROBE,
                ADMIT_QUARANTINED,
            )

            mode = gov.admit()
            if mode == ADMIT_QUARANTINED:
                # quarantined device (chaos tpu_fail, shadow-verification
                # mismatch, or repeated dispatch failure): the daemon
                # must keep producing routes — scalar oracle takes over
                self.num_fallback_injected += 1
                return self._scalar_fallback(area_link_states, prefix_state)
            probe = mode == ADMIT_PROBE
        elif self.device_failed:
            self.num_fallback_injected += 1
            return self._scalar_fallback(area_link_states, prefix_state)
        # the device kernel implements the enabled best-route-selection
        # semantics for both distance algorithms; anything else goes
        # through the scalar oracle for exactness
        if (
            not area_link_states
            or not self.solver.enable_best_route_selection
            or self.solver.route_selection_algorithm
            not in (
                RouteComputationRules.SHORTEST_DISTANCE,
                RouteComputationRules.PER_AREA_SHORTEST_DISTANCE,
            )
        ):
            if probe:
                gov.abort_probe()
            return self._scalar_fallback(area_link_states, prefix_state)
        try:
            if self.min_device_prefixes is None:
                if not self._device_worth_it(area_link_states, prefix_state):
                    if probe:
                        gov.abort_probe()
                    return self._scalar_fallback(
                        area_link_states, prefix_state, counter="small"
                    )
            elif (
                self.min_device_prefixes
                and len(prefix_state.prefixes()) < self.min_device_prefixes
            ):
                if probe:
                    gov.abort_probe()
                return self._scalar_fallback(
                    area_link_states, prefix_state, counter="small"
                )
            db = self._build_device(
                area_link_states,
                prefix_state,
                changed_prefixes,
                force_full,
                delta_class=(
                    "structural"
                    if structural_delta
                    else ("perturbation" if warm_delta else None)
                ),
            )
        except ValueError:
            # capacity/shape fallback (e.g. a prefix with more candidates
            # than the largest device bucket): a DATA-scale limit, not a
            # device-health signal — fall back without scoring the breaker
            # (abort_probe also releases any armed per-chip probe shard)
            if gov is not None:
                gov.abort_probe()
            return self._scalar_fallback(area_link_states, prefix_state)
        except Exception as e:  # noqa: BLE001 - organic dispatch failure
            if gov is None:
                raise  # legacy (resilience disabled): crash loud
            # the failure trips the SAME latch chaos uses: the breaker
            # counts it, and past the threshold the device is quarantined
            # instead of being re-paid on every rebuild
            self.num_dispatch_errors += 1
            gov.record_dispatch_failure(e)
            return self._scalar_fallback(area_link_states, prefix_state)
        if db is None:
            # vantage not present in any area topology: nothing was
            # computed, nothing to verify — release an acquired probe
            if gov is not None:
                gov.abort_probe()
            return None
        if gov is not None:
            db, from_device = gov.after_device_build(
                db, area_link_states, prefix_state, probe=probe
            )
            if not from_device:
                # shadow verification replaced a corrupt device result
                # with the scalar oracle's: every incremental base
                # derived from device output is untrustworthy, and the
                # caller must diff this build against its WHOLE previous
                # RouteDb (corrupt entries from unsampled builds since
                # the last verified one must be purged, not just the
                # changed prefixes)
                self._last_db = None
                self._table_synced = False
                self._full_replace = True
                # the swap proves the device (or a chip) lied: nothing
                # device-resident is trustworthy as a warm base, and the
                # patched-changed-set guarantee no longer holds either
                self._last_changed_prefixes = None
                self._purge_warm("full_replace")
                return db
        if cache_result:
            self._last_db = db
        else:
            self._last_db = None
        return db

    def take_full_replace(self) -> bool:
        fr, self._full_replace = self._full_replace, False
        return fr

    def take_last_changed_prefixes(self) -> Optional[Set[str]]:
        out, self._last_changed_prefixes = self._last_changed_prefixes, None
        return out

    # -- warm-start generation-delta context -------------------------------

    def _purge_warm(self, reason: str, suspect: bool = True) -> None:
        """Drop the warm-rebuild context (previous generation's tables +
        host mirrors) and force the next device build through shadow
        verification.  Triggers: corruption injection (``tpu_corrupt``,
        whole-backend or chip-scoped), any quarantine re-pack, the
        full-replace swap, and structural/shape deltas.  ``suspect``
        (the default) additionally drops the device-resident SPF table
        cache and its per-chip replicas, so the next device build truly
        solves COLD — we never reuse device state a corruption event
        may have touched.  Size/housekeeping purges pass suspect=False
        and keep the (trusted) tables.  Idempotent — only an actual
        drop counts as a purge."""
        key = reason.split(":", 1)[0]
        self._warm_purge_reasons[key] = (
            self._warm_purge_reasons.get(key, 0) + 1
        )
        if suspect:
            self._spf_tables = None
            self._spf_enc = None
            self._spf_degree = None
            self._spf_replicas = {}
            # the full-build delta context is device residency too: a
            # suspect device must not vouch for "row unchanged"
            self._prev_sel = None
        if self._warm_ctx is None and self._warm_changed_nodes is None:
            return
        self._warm_ctx = None
        self._warm_changed_nodes = None
        self._warm_base_enc = None
        self.num_warm_purges += 1
        if self.governor is not None:
            self.governor.request_shadow_check(reason)

    def _warm_fallback(
        self, reason: str, delta_class: Optional[str] = None
    ) -> None:
        self.num_warm_cold_fallbacks += 1
        self._warm_fallback_reasons[reason] = (
            self._warm_fallback_reasons.get(reason, 0) + 1
        )
        if delta_class in self._warm_class_fallbacks:
            self._warm_class_fallbacks[delta_class] += 1
            by = self._warm_class_fallback_reasons[delta_class]
            by[reason] = by.get(reason, 0) + 1

    def _warm_hit(self, delta_class: Optional[str]) -> None:
        self.num_warm_builds += 1
        if delta_class in self._warm_class_builds:
            self._warm_class_builds[delta_class] += 1

    # -- the device pool (per-chip failure domains) ------------------------

    @property
    def pool(self):
        """Lazily-built DevicePool over the visible jax devices: the
        unit of health governance.  Built on first touch so embedders
        that never build routes never pay jax platform init."""
        if self._pool is None:
            from openr_tpu.parallel.mesh import DevicePool

            self._pool = DevicePool(
                max_devices=(
                    1 if not self._parallel_enabled else self._max_devices
                )
            )
        return self._pool

    def _use_pool(self) -> bool:
        """Multi-chip dispatch active: more than one chip in the pool.
        Single-device pools keep the zero-copy legacy dispatch path."""
        return self._parallel_enabled and self.pool.size > 1

    def dispatch_pool(self):
        """The DevicePool when multi-chip dispatch is active, else None
        — what Decision hands the fleet / what-if engines so their
        batches route data-parallel over the same health-governed chips
        route builds use."""
        return self.pool if self._use_pool() else None

    def last_build_attribution(self):
        """``(devices_with_fresh_rows, device_of_prefix)`` for the last
        device build, or None when it was not pool-attributed (legacy
        single-device path, scalar fallback).  ``device_of_prefix``
        returns the pool index that computed a prefix's row in THAT
        build, or None for rows the build did not freshly compute
        (static overlay, stale incremental bases) — the governor treats
        those as unattributable and falls back to the whole-backend
        quarantine."""
        table = self._attr_table
        if table is None:
            return None
        if self._attr_rows is not None:
            rows = self._attr_rows
            devs = sorted(set(rows.values()))

            def dev_of(prefix, _rows=rows, _table=table):
                r = _table.pid.get(prefix)
                return None if r is None else _rows.get(r)

            return devs, dev_of
        plan = self._attr_plan
        devs = [
            d
            for d, lo, hi in plan
            if any(p is not None for p in table.row_prefix[lo:hi])
        ]

        def dev_of(prefix, _plan=plan, _table=table):
            r = _table.pid.get(prefix)
            if r is None:
                return None
            for d, lo, hi in _plan:
                if lo <= r < hi:
                    return d
            return None

        return devs, dev_of

    def inject_device_failure(self, failed: bool) -> None:
        """Force (or clear) the device-outage path: while set, every build
        is a `_scalar_fallback`.  Used by operators draining a sick
        accelerator; clearing is an immediate FORCE-restore (chaos heals
        go through `governor.request_probe` instead, so recovery is
        verified by a probe solve)."""
        if self.governor is not None:
            if failed:
                self.governor.force_quarantine(reason="injected")
            else:
                self.governor.force_restore(reason="injected_clear")
            return
        self.device_failed = failed

    def inject_silent_corruption(
        self, corrupt: bool, device_index: Optional[int] = None
    ) -> None:
        """Chaos ``tpu_corrupt``: perturb fetched kernel outputs WITHOUT
        raising — wrong-but-plausible route metrics reach the decode
        path, modeling accelerator silent data corruption.  Detection is
        the governor's job (shadow verification), never this flag's.
        ``device_index`` scopes the lie to the shards computed on ONE
        pool chip (the per-chip SDC model); None keeps the legacy
        every-shard corruption."""
        if device_index is None:
            self._sdc_inject = corrupt
        elif corrupt:
            self._sdc_devices.add(int(device_index))
        else:
            self._sdc_devices.discard(int(device_index))
        if corrupt:
            # a lying accelerator means nothing device-resident can seed
            # a warm rebuild: the next generation solves cold, and the
            # governor is asked to shadow-verify it
            self._purge_warm(
                "tpu_corrupt"
                if device_index is None
                else f"tpu_corrupt:dev{int(device_index)}"
            )

    def _sdc_active_for(self, device_index: int) -> bool:
        return self._sdc_inject or device_index in self._sdc_devices

    def counter_snapshot(self) -> Dict[str, float]:
        out = {
            "decision.backend.device": 1.0,
            "decision.backend.device_failed": 1.0 if self.device_failed else 0.0,
            "decision.backend.num_device_builds": float(self.num_device_builds),
            "decision.backend.num_scalar_builds": float(self.num_scalar_builds),
            "decision.backend.num_small_scalar_builds": float(
                self.num_small_scalar_builds
            ),
            "decision.backend.num_incremental_builds": float(
                self.num_incremental_builds
            ),
            "decision.backend.num_fallback_cand_overflow": float(
                self.num_fallback_cand_overflow
            ),
            "decision.backend.num_fallback_injected": float(
                self.num_fallback_injected
            ),
            "decision.backend.num_dispatch_errors": float(
                self.num_dispatch_errors
            ),
            "decision.backend.sdc_injected": (
                1.0 if (self._sdc_inject or self._sdc_devices) else 0.0
            ),
            # warm-start generation-delta rebuild telemetry (ISSUE 9):
            # warm_hit_ratio = warm table solves / warm-classified
            # topology ticks — the operator's first read on whether the
            # fleet's churn profile is actually warm-eligible
            "decision.backend.warm_enabled": 1.0 if self._warm_enabled else 0.0,
            "decision.backend.warm_context_ready": (
                1.0 if self._warm_ctx is not None else 0.0
            ),
            "decision.backend.warm_builds": float(self.num_warm_builds),
            "decision.backend.warm_subgraph_builds": float(
                self.num_warm_subgraph_builds
            ),
            "decision.backend.warm_selective_builds": float(
                self.num_warm_selective_builds
            ),
            "decision.backend.warm_cold_fallbacks": float(
                self.num_warm_cold_fallbacks
            ),
            "decision.backend.warm_purges": float(self.num_warm_purges),
            "decision.backend.warm_encode_patches": float(
                self.num_encode_patches
            ),
            "decision.backend.warm_hit_ratio": (
                self.num_warm_builds
                / max(1, self.num_warm_builds + self.num_warm_cold_fallbacks)
            ),
            "decision.backend.warm_last_est_depth": float(
                self.warm_last_est_depth
            ),
            "decision.backend.warm_last_reset_nodes": float(
                self.warm_last_reset_nodes
            ),
            # ISSUE-12 split: the structural (membership-churn) ratio is
            # what a rolling fleet upgrade lives on; perturbation ticks
            # must not be allowed to mask a structural cold wall
            "decision.backend.warm_builds.perturbation": float(
                self._warm_class_builds["perturbation"]
            ),
            "decision.backend.warm_builds.structural": float(
                self._warm_class_builds["structural"]
            ),
            "decision.backend.warm_cold_fallbacks.perturbation": float(
                self._warm_class_fallbacks["perturbation"]
            ),
            "decision.backend.warm_cold_fallbacks.structural": float(
                self._warm_class_fallbacks["structural"]
            ),
            "decision.backend.warm_hit_ratio.perturbation": (
                self._warm_class_builds["perturbation"]
                / max(
                    1,
                    self._warm_class_builds["perturbation"]
                    + self._warm_class_fallbacks["perturbation"],
                )
            ),
            "decision.backend.warm_hit_ratio.structural": (
                self._warm_class_builds["structural"]
                / max(
                    1,
                    self._warm_class_builds["structural"]
                    + self._warm_class_fallbacks["structural"],
                )
            ),
            "decision.backend.warm_encode_slot_patches": float(
                self.num_encode_slot_patches
            ),
            # streamed-pipeline + on-device delta-extraction telemetry
            # (ISSUE 11): delta_rows_skipped / (fetched + skipped) is
            # the fraction of the route table that never crossed the
            # host boundary on full rebuilds
            "decision.backend.stream_builds": float(self.num_stream_builds),
            "decision.backend.stream_repacks": float(
                self.num_stream_repacks
            ),
            "decision.backend.delta_builds": float(self.num_delta_builds),
            "decision.backend.delta_rows_fetched": float(
                self.num_delta_rows_fetched
            ),
            "decision.backend.delta_rows_skipped": float(
                self.num_delta_rows_skipped
            ),
        }
        for reason, n in sorted(self._slot_decline_reasons.items()):
            out[f"decision.backend.slot_decline.{reason}"] = float(n)
        for cls, reasons in sorted(
            self._warm_class_fallback_reasons.items()
        ):
            for reason, n in sorted(reasons.items()):
                out[
                    f"decision.backend.warm_fallback.{cls}.{reason}"
                ] = float(n)
        for reason, n in sorted(self._warm_purge_reasons.items()):
            out[f"decision.backend.warm_purge.{reason}"] = float(n)
        # content-hash RepairPlan cache (ops.repair): the what-if and
        # capacity-sweep planners' reuse surface — hits prove prefix
        # churn isn't restarting planning, evictions + size prove the
        # config cap holds under world churn
        from openr_tpu.ops.repair import plan_cache_gauges

        for k, v in plan_cache_gauges().items():
            out[f"decision.backend.{k}"] = v
        if self._pool is not None:
            # only report pool gauges once the pool actually exists — a
            # Monitor sweep must never be the thing that boots jax
            out.update(self._pool.counter_snapshot("decision.backend.pool"))
        return out

    def _device_worth_it(self, area_link_states, prefix_state) -> bool:
        """Auto cutover: device iff the estimated scalar build cost
        exceeds the measured device dispatch overhead.  Work items =
        prefix rows + directed edges; both sides only need order-of-
        magnitude accuracy (the knob this replaces defaulted to 'always
        device', which cost small grids ~25x over scalar on a tunneled
        chip — BENCH_SUITE r3 grid16 row)."""
        if self.auto_dispatch_rt_ms is None:
            self.auto_dispatch_rt_ms = measure_dispatch_rt_ms()
        work = estimate_scalar_work_items(area_link_states, prefix_state)
        scalar_us = work * self.SCALAR_US_PER_ITEM
        device_us = (
            self.DEVICE_OVERHEAD_TRIPS * self.auto_dispatch_rt_ms * 1000.0
        )
        return scalar_us >= device_us

    def _scalar_fallback(
        self, area_link_states, prefix_state, counter: str = "scalar"
    ):
        """Delegate one build to the scalar solver and invalidate every
        incremental base (the candidate table misses this tick's churn)."""
        if counter == "small":
            self.num_small_scalar_builds += 1
        else:
            self.num_scalar_builds += 1
        self._last_db = None
        self._table_synced = False
        self._attr_table = None  # nothing device-computed to attribute
        self._prev_sel = None  # resident outputs no longer match _last_db
        return self.solver.build_route_db(area_link_states, prefix_state)

    # -- encoding (cached across prefix-churn rebuilds) --------------------

    def _encoded(self, area_link_states, me):
        from openr_tpu.ops.csr import encode_multi_area

        cache_key = tuple(
            (a, area_link_states[a].topology_seq)
            for a in sorted(area_link_states)
        )
        cached = self._enc_cache.get(cache_key)
        # pin the LinkState objects themselves: identity must be compared
        # via held references (a bare id() could be reused by a
        # replacement object after GC and serve stale arrays)
        if cached is not None and all(
            ls_ref is area_link_states[a]
            for a, ls_ref in zip(sorted(area_link_states), cached[0])
        ):
            self.num_encode_hits += 1
            return cached[1]
        enc = None
        self._last_encode_kind = "cold"
        if self._warm_enabled and self._enc_cache:
            # perturbation ticks (the overwhelming topology-churn class)
            # refresh only the weight/validity/drain columns; membership
            # churn (node join/leave, link add/remove — a rolling
            # restart's delta class) takes the slot-stable structural
            # patch.  Both share every layout array with the previous
            # encoding — the full re-sort/re-intern/re-expand pass is
            # most of the warm rebuild's host budget at 4096 nodes.
            from openr_tpu.ops.csr import patch_encoded_multi_area_slots

            (prev_ls, prev_enc) = next(iter(self._enc_cache.values()))
            enc, kind, reason = patch_encoded_multi_area_slots(
                prev_enc, area_link_states, me
            )
            if enc is not None:
                self._last_encode_kind = kind
                if kind == "slot":
                    self.num_encode_slot_patches += 1
                else:
                    self.num_encode_patches += 1
            elif reason is not None:
                self._slot_decline_reasons[reason] = (
                    self._slot_decline_reasons.get(reason, 0) + 1
                )
        if enc is None:
            enc = encode_multi_area(
                area_link_states, me, node_buckets=self.node_buckets
            )
        self._enc_cache = {
            cache_key: (
                [area_link_states[a] for a in sorted(area_link_states)],
                enc,
            )
        }
        self._ksp2_engines = {}
        self.num_encodes += 1
        return enc

    def _ksp2_engine(self, area: str, link_state, topo):
        from openr_tpu.decision.ksp2 import Ksp2DeviceEngine

        key = (area, link_state.topology_seq)
        eng = self._ksp2_engines.get(key)
        if eng is None or eng.link_state is not link_state or eng.topo is not topo:
            eng = Ksp2DeviceEngine(link_state, topo, self.solver.my_node_name)
            self._ksp2_engines[key] = eng
        return eng

    #: per-platform cold-SPF kernel preference (the ROADMAP policy
    #: hook): maps a jax backend platform name ("cpu"/"tpu"/"gpu", or
    #: "default") to "dense" (the gather in-edge formulation) or
    #: "segment" (the ``indices_are_sorted`` segment-reduction path).
    #: Unset platforms use dense whenever the encoding carries the
    #: in-edge matrix — the behavior every host-platform bench was
    #: measured under; both kernels are kept bit-parity-tested, so a
    #: TPU profiling result flips one entry here, not a code path.
    KERNEL_PREFERENCE: Dict[str, str] = {}

    def _spf_kernel_preference(self) -> str:
        import jax

        pref = self.KERNEL_PREFERENCE.get(jax.default_backend())
        if pref is None:
            pref = self.KERNEL_PREFERENCE.get("default", "dense")
        return pref

    def _spf(self, enc, max_degree: int, delta_class=None):
        """Device (dist [A,V], nh [A,V,D]) tables, cached per encoding.

        On a topology tick, a warm-eligible delta (the ``delta_class``
        hint — "perturbation" or "structural" — plus structural
        compatibility against the retained previous generation)
        re-relaxes only the perturbed frontier from the previous
        generation's device-resident tables (the ISSUE-9 warm-start
        path; ISSUE 12 extends it to slot-stable membership churn);
        everything else solves cold.  Either way the new generation's
        tables (plus small host mirrors for the NEXT delta's planning)
        are retained as the warm context."""
        import jax.numpy as jnp

        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.ops.route_select import multi_area_spf_tables

        if (
            self._spf_tables is not None
            and self._spf_enc is enc
            and self._spf_degree == max_degree
        ):
            return self._spf_tables
        from openr_tpu.tracing import pipeline

        self._warm_solved = False
        self._warm_changed_nodes = None
        self._warm_rounds = None
        dist = nh = None
        if (
            self._warm_enabled
            and delta_class is not None
            and self._warm_ctx is not None
        ):
            dist, nh = self._warm_spf(enc, max_degree, delta_class)
        elif self._warm_enabled and delta_class is not None:
            # warm-classified tick but the context was purged (corruption,
            # quarantine re-pack, full replace): this build solves cold
            # and re-establishes the context
            self._warm_fallback("no_context", delta_class)
        elif self._warm_enabled and self._warm_ctx is not None:
            # a topology tick the hint classified cold (static/policy
            # coincidence, first build): count it so the warm-hit ratio
            # reflects reality
            self._warm_fallback("unclassified")
        if dist is None:
            if enc.has_dense and self._spf_kernel_preference() != "segment":
                # dense in-edge gather formulation: the cold fixpoints
                # run without scatter (the segment loops were ~95% of a
                # grid4096 cold rebuild wall on host platforms, hiding
                # inside the device_get barrier — BENCH_PIPELINE_r01)
                from openr_tpu.ops.route_select import (
                    multi_area_spf_tables_dense,
                )

                with self.probe.phase(pipeline.TRANSFER):
                    args = (
                        jnp.asarray(enc.in_src),
                        jnp.asarray(enc.in_w),
                        jnp.asarray(enc.in_ok),
                        jnp.asarray(enc.in_rank),
                        jnp.asarray(enc.in_has),
                        jnp.asarray(enc.overloaded),
                        jnp.asarray(enc.roots),
                    )
                with self.probe.phase(pipeline.DEVICE_COMPUTE):
                    dist, nh = call_jit_guarded(
                        multi_area_spf_tables_dense,
                        *args,
                        max_degree=max_degree,
                    )
            else:
                with self.probe.phase(pipeline.TRANSFER):
                    args = (
                        jnp.asarray(enc.src),
                        jnp.asarray(enc.dst),
                        jnp.asarray(enc.w),
                        jnp.asarray(enc.edge_ok),
                        jnp.asarray(enc.overloaded),
                        jnp.asarray(enc.roots),
                    )
                with self.probe.phase(pipeline.DEVICE_COMPUTE):
                    dist, nh = call_jit_guarded(
                        multi_area_spf_tables, *args, max_degree=max_degree
                    )
        # keep soft/overloaded device-resident alongside (selection inputs)
        with self.probe.phase(pipeline.TRANSFER):
            soft = jnp.asarray(enc.soft)
            ovl = jnp.asarray(enc.overloaded)
        self._spf_tables = (dist, nh, ovl, soft)
        self._spf_enc = enc
        self._spf_degree = max_degree
        if self._warm_enabled:
            self._refresh_warm_ctx(enc, max_degree)
        return self._spf_tables

    #: warm-context host mirrors beyond this size are not worth the
    #: per-generation fetch (the warm win targets the debounce budget)
    WARM_MAX_TABLE_BYTES = 64 << 20

    def _warm_spf(self, enc, max_degree: int, delta_class=None):
        """Attempt the generation-delta warm solve.  Returns (dist, nh)
        device tables, or (None, None) after counting a cold fallback."""
        import jax
        import jax.numpy as jnp

        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.ops.repair import plan_generation_delta
        from openr_tpu.ops.route_select import warm_multi_area_spf_tables
        from openr_tpu.tracing import pipeline

        ctx = self._warm_ctx
        with self.probe.phase(pipeline.WARM_PLAN):
            if ctx["degree"] != max_degree:
                self._warm_fallback("degree_bucket", delta_class)
                return None, None
            old_enc = ctx["enc"]
            if old_enc.areas != enc.areas:
                self._warm_fallback("structural", delta_class)
                return None, None
            if ctx["dist"] is None:
                # lazily materialize the previous generation's host
                # mirrors — cold builds store device references only, so
                # the common cold path pays no fetch; by the time a
                # warm delta needs them the tables are long since ready
                dist_h, nh_h = jax.device_get(ctx["tables"])
                ctx["dist"] = np.asarray(dist_h)
                ctx["nh"] = np.asarray(nh_h)
            plans = []
            for ai, (old_topo, new_topo) in enumerate(
                zip(old_enc.topos, enc.topos)
            ):
                if new_topo.padded_edges != old_topo.padded_edges:
                    plans = None
                    self._warm_fallback("edge_bucket", delta_class)
                    break
                # slot-patched chain: layout identity between the two
                # generations is proven by ARRAY identity (the slot
                # patch shares src/dst/link_index with its base), so
                # symbol renames are tolerated and membership-churned
                # slots ride the forced reset set (tombstoned rows
                # seed at +inf)
                trust = (
                    new_topo.src is old_topo.src
                    and new_topo.link_index is old_topo.link_index
                )
                delta = plan_generation_delta(
                    old_topo,
                    int(enc.roots[ai]),
                    ctx["dist"][ai],
                    new_topo,
                    force_reset=(
                        new_topo.slot_changed if trust else None
                    ),
                    trust_layout=trust,
                )
                if delta is None:
                    plans = None
                    self._warm_fallback("structural", delta_class)
                    break
                plans.append(delta)
            if plans is None:
                return None, None
            reset = np.stack([p.reset for p in plans])
            lane_keep = np.asarray(
                [p.lanes_compatible for p in plans], bool
            )
            self.warm_last_est_depth = max(p.est_depth for p in plans)
            self.warm_last_reset_nodes = int(sum(p.num_reset for p in plans))
            # bounded-subgraph eligibility: pure weakening (no edge got
            # cheaper/added) with an unchanged root lane basis — then
            # the per-round working set is the perturbed frontier's
            # in-edges, independent of topology size
            use_sub = all(
                (not p.has_improvements) and p.lanes_compatible
                for p in plans
            )
            sub_args = None
            if use_sub:
                sub_args = self._pack_sub_edges(enc, plans)
        prev_dist, prev_nh = ctx["tables"]
        if sub_args is not None:
            from openr_tpu.ops.route_select import (
                warm_multi_area_subgraph_tables,
            )

            with self.probe.phase(pipeline.TRANSFER):
                args = tuple(jnp.asarray(a) for a in sub_args) + (
                    prev_dist,
                    prev_nh,
                    jnp.asarray(reset),
                )
            with self.probe.phase(pipeline.WARM_REPAIR):
                dist, nh, rounds_d, rounds_l = call_jit_guarded(
                    warm_multi_area_subgraph_tables,
                    *args,
                    max_degree=max_degree,
                )
            self.num_warm_subgraph_builds += 1
        else:
            with self.probe.phase(pipeline.TRANSFER):
                args = (
                    jnp.asarray(enc.src),
                    jnp.asarray(enc.dst),
                    jnp.asarray(enc.w),
                    jnp.asarray(enc.edge_ok),
                    jnp.asarray(enc.overloaded),
                    jnp.asarray(enc.roots),
                    prev_dist,
                    prev_nh,
                    jnp.asarray(reset),
                    jnp.asarray(lane_keep),
                )
            with self.probe.phase(pipeline.WARM_REPAIR):
                dist, nh, rounds_d, rounds_l = call_jit_guarded(
                    warm_multi_area_spf_tables, *args, max_degree=max_degree
                )
        self._warm_solved = True
        self._warm_base_enc = old_enc
        self._warm_rounds = (rounds_d, rounds_l)
        self._warm_hit(delta_class)
        return dist, nh

    def _pack_sub_edges(self, enc, plans):
        """[A, Es]-bucketed sub-edge arrays (src, dst, w, ok, lane rank)
        for the bounded warm-repair kernel.  Positions come dst-sorted
        from the planner; pads keep dst non-decreasing and carry
        ok=False so the kernel's segment reductions ignore them."""
        es_max = max(
            (len(p.sub_edges) for p in plans), default=0
        )
        buckets = [
            b
            for b in SUB_EDGE_BUCKETS
            if b < enc.topos[0].padded_edges
        ] + [enc.topos[0].padded_edges]
        es_pad = next(b for b in buckets if b >= max(es_max, 1))
        A = enc.num_areas
        V = enc.topos[0].padded_nodes
        src_sub = np.zeros((A, es_pad), np.int32)
        dst_sub = np.full((A, es_pad), V - 1, np.int32)
        w_sub = np.full((A, es_pad), np.float32(np.inf), np.float32)
        ok_sub = np.zeros((A, es_pad), bool)
        rank_sub = np.full((A, es_pad), -1, np.int32)
        for ai, (topo, plan) in enumerate(zip(enc.topos, plans)):
            pos = plan.sub_edges
            n = len(pos)
            if not n:
                continue
            root = int(enc.roots[ai])
            transit = (~topo.overloaded) | (
                np.arange(V) == root
            )
            okf = topo.edge_ok & transit[topo.src]
            rank_full = np.full(topo.padded_edges, -1, np.int32)
            root_out = np.nonzero(
                (topo.src == root) & (topo.link_index >= 0)
            )[0]
            rank_full[root_out] = np.arange(len(root_out), dtype=np.int32)
            src_sub[ai, :n] = topo.src[pos]
            dst_sub[ai, :n] = topo.dst[pos]
            w_sub[ai, :n] = topo.w[pos]
            ok_sub[ai, :n] = okf[pos]
            rank_sub[ai, :n] = rank_full[pos]
            # keep dst non-decreasing through the pad tail
            dst_sub[ai, n:] = max(int(topo.dst[pos[-1]]), 0)
        return src_sub, dst_sub, w_sub, ok_sub, rank_sub

    def _refresh_warm_ctx(self, enc, max_degree: int) -> None:
        """Retain THIS generation's tables as the next delta's warm base.
        Cold builds store device references ONLY (zero added fetch/sync
        on the cold path; host mirrors materialize lazily at the next
        warm delta's planning).  Warm builds fetch the new mirrors
        immediately — the selective-selection path needs the
        changed-node diff before it can pick its rows."""
        import jax

        from openr_tpu.tracing import pipeline

        dist_d, nh_d = self._spf_tables[0], self._spf_tables[1]
        table_bytes = int(
            np.prod(dist_d.shape) * 4 + np.prod(nh_d.shape)
        )
        if table_bytes > self.WARM_MAX_TABLE_BYTES:
            # housekeeping, not suspicion: the tables stay trusted and
            # cached; only warm seeding is declined at this size
            self._purge_warm("table_too_large", suspect=False)
            return
        dist_h = nh_h = None
        prev = self._warm_ctx
        if self._warm_solved:
            with self.probe.phase(pipeline.WARM_PLAN):
                dist_h, nh_h = jax.device_get((dist_d, nh_d))
                dist_h = np.asarray(dist_h)
                nh_h = np.asarray(nh_h)
                if (
                    prev is not None
                    and prev["dist"] is not None
                    and prev["dist"].shape == dist_h.shape
                    and prev["nh"].shape == nh_h.shape
                ):
                    # per-node change mask vs the previous generation —
                    # selection outputs can only move for prefixes whose
                    # candidate rows read a changed (dist, lane, drain)
                    # cell
                    changed = (prev["dist"] != dist_h) | (
                        prev["nh"] != nh_h
                    ).any(axis=2)
                    changed |= prev["enc"].overloaded != enc.overloaded
                    changed |= prev["enc"].soft != enc.soft
                    # slot-membership churn: a renamed slot can keep
                    # identical dist/lanes (replacement node, same
                    # links) yet its NAME — which decode embeds in
                    # routes — changed; force its rows to re-select
                    for ai, t in enumerate(enc.topos):
                        if t.slot_changed is not None:
                            changed[ai] |= t.slot_changed
                    self._warm_changed_nodes = changed
                if self._warm_rounds is not None:
                    rd, rl = jax.device_get(self._warm_rounds)
                    self.warm_last_rounds = (
                        int(np.max(rd)),
                        int(np.max(rl)),
                    )
                    self._warm_rounds = None
        self._warm_ctx = {
            "enc": enc,
            "dist": dist_h,
            "nh": nh_h,
            "degree": max_degree,
            "tables": (dist_d, nh_d),
        }

    # -- multi-chip dispatch ----------------------------------------------

    def _dispatch_device_set(self):
        """(device_indices, probe_device) for this build: the pool's
        healthy chips, plus at most one quarantined chip whose breaker
        admitted a half-open probe shard (governor-armed)."""
        devices = probe = None
        if self.governor is not None:
            devices, probe = self.governor.dispatch_devices()
        if devices is None:
            devices = self.pool.healthy_indices() or [0]
        return devices, probe

    def _plan_full_dispatch(self, n_rows: int, n_active: int):
        """Shard plan [(device, row_lo, row_hi)] for a full selection
        batch.  Boundaries split the ACTIVE row range (rows actually
        holding prefixes) evenly — prefixes fill the candidate table
        head-first, so splitting raw bucket capacity would hand real
        work to the lead chips and dead padding to the rest; the dead
        tail rides the last shard.  `min_shard_rows` collapses tiny
        batches onto the lead chip — dispatch overhead and per-shape
        compiles dominate below it — but an armed probe chip always
        keeps a shard (the probe must actually exercise the chip).
        Single-chip pools plan ONE shard on the lead chip, so every
        full build flows through the same streamed dispatcher."""
        self._plan_probe = None
        if not self._use_pool():
            lead = self.pool.lead_index()
            return [(lead if lead is not None else 0, 0, n_rows)]
        devices, probe = self._dispatch_device_set()
        msr = self._min_shard_rows
        if msr > 0 and len(devices) > 1:
            n_use = max(1, min(len(devices), n_active // msr))
            if n_use < len(devices):
                keep = devices[:n_use]
                if probe is not None and probe not in keep:
                    keep[-1] = probe
                devices = keep
        plan = self.pool.shard_ranges(max(n_active, 1), devices)
        # the dead tail (bucket padding past the last occupied row)
        # decodes to nothing; append it to the final shard
        dev, lo, _hi = plan[-1]
        plan[-1] = (dev, lo, n_rows)
        if self.governor is not None:
            self.governor.confirm_plan([d for d, _lo, _hi in plan])
        self._plan_probe = probe
        return plan

    def _replicated_tables(self, dev_index: int, tables: tuple) -> tuple:
        """Per-device replica of the device-resident SPF tables, cached
        by table identity so steady-state rebuilds pay zero copies.  A
        pool health transition (quarantine/restore) re-packs shard
        ownership via ``DevicePool.shard_ranges`` — replicas pinned to
        now-unhealthy chips are dropped here so stale HBM residency
        never outlives the re-pack."""
        import jax

        pool = self.pool
        if self._replica_health_seq != pool.health_seq:
            self._spf_replicas = {
                k: v
                for k, v in self._spf_replicas.items()
                if pool.is_healthy(k)
            }
            self._replica_health_seq = pool.health_seq
        cached = self._spf_replicas.get(dev_index)
        if cached is not None and cached[0] is tables:
            return cached[1]
        from openr_tpu.tracing import pipeline

        dev = self.pool.device(dev_index)
        with self.probe.phase(pipeline.TRANSFER, device=dev_index):
            rep = tuple(jax.device_put(t, dev) for t in tables)
        self._spf_replicas[dev_index] = (tables, rep)
        return rep

    def _stream_row_shards(self, dv, tables, per_area, plan, delta_ctx):
        """Streamed, double-buffered shard dispatch — the replacement
        for the old dispatch-all-then-ONE-blocking-device_get barrier
        that BENCH_PIPELINE_r01 indicted (device_get ~1.5s of a ~1.7s
        grid4096 wall).

        * **double buffer**: shard N+1's pad/transfer/dispatch runs
          while shard N solves (dispatches are async); the DevicePool
          in-flight ledger caps undrained work per chip at STREAM_SLOTS
          so a committed dispatch never queues behind — or waits on —
          an unrelated chip.
        * **streamed completion**: shards drain one at a time in
          COMPLETION order (``is_ready`` poll, then a per-shard
          ``stream_drain`` wait charged ONLY to the completing chip);
          the caller decodes each shard while the rest still solve.
        * **on-device delta extraction** (``delta_ctx``): the fused
          select+diff kernel compares this generation's outputs against
          the previous build's device-resident outputs; only the
          changed-row mask and a compacted gather of changed rows cross
          the host boundary (``device_select`` phase) — full tables are
          fetched only when most of a shard moved.
        * **mid-stream re-pack**: a shard failing at drain time
          quarantines ITS chip (``governor.record_stream_failure``) and
          re-dispatches exactly its row range onto the lead survivor —
          no rows dropped, none duplicated; a failing PROBE shard
          raises instead (the whole build falls back so the governor
          scores the probe).

        Yields per-shard dicts in completion order:
        ``{"dev", "lo", "hi", "use", "shortest", "lanes", "valid",
        "rows"}`` — ``rows`` is None on a full fetch (arrays cover the
        whole shard) or the LOCAL changed-row indices (arrays compacted
        to that order).  Each shard pads to a common row count so the
        jit cache sees one shape per plan size; pad rows carry
        cand_ok=False and decode to nothing."""
        import jax
        import jax.numpy as jnp

        from openr_tpu.ops import jit_guard
        from openr_tpu.ops.csr import bucket_for
        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.ops.route_select import (
            gather_selection_rows,
            multi_area_select_delta_from_tables,
            multi_area_select_from_tables,
        )
        from openr_tpu.tracing import pipeline

        width = max(hi - lo for _d, lo, hi in plan)

        def pad(a, lo, hi):
            if hi - lo == width:
                return a[lo:hi]
            out = np.empty((width,) + a.shape[1:], a.dtype)
            out[: hi - lo] = a[lo:hi]
            out[hi - lo :] = a[lo]
            return out

        def dispatch(dev_index, lo, hi, use_delta):
            dev = self.pool.device(dev_index)
            td, tn, to, ts = self._replicated_tables(dev_index, tables)
            with self.probe.phase(pipeline.PAD_PACK, device=dev_index):
                ok = np.zeros(
                    (width,) + dv.cand_ok.shape[1:], dv.cand_ok.dtype
                )
                ok[: hi - lo] = dv.cand_ok[lo:hi]
                padded = (
                    pad(dv.cand_area, lo, hi),
                    pad(dv.cand_node, lo, hi),
                    ok,
                    pad(dv.drain_metric, lo, hi),
                    pad(dv.path_pref, lo, hi),
                    pad(dv.source_pref, lo, hi),
                    pad(dv.distance, lo, hi),
                    pad(dv.cand_node_in_area, lo, hi),
                )
            with self.probe.phase(pipeline.TRANSFER, device=dev_index):
                shard_args = tuple(
                    jax.device_put(a, dev) for a in padded
                )
                if use_delta:
                    nc_dev = jax.device_put(
                        delta_ctx["node_changed"], dev
                    )
            # a COMMITTED computation on its own chip: the kernel span
            # and the phase sample both carry the device, so a wrong
            # output row and a slow dispatch attribute to the same chip
            with self.probe.phase(
                pipeline.DEVICE_COMPUTE, device=dev_index
            ), jit_guard.dispatch_device(dev_index):
                if use_delta:
                    u, s, l, v, ch = call_jit_guarded(
                        multi_area_select_delta_from_tables,
                        td,
                        tn,
                        to,
                        ts,
                        *shard_args,
                        *delta_ctx["shards"][(dev_index, lo, hi)],
                        nc_dev,
                        per_area_distance=per_area,
                    )
                    outs, ch = (u, s, l, v), ch
                else:
                    outs = call_jit_guarded(
                        multi_area_select_from_tables,
                        td,
                        tn,
                        to,
                        ts,
                        *shard_args,
                        per_area_distance=per_area,
                    )
                    ch = None
            self.pool.note_inflight(dev_index)
            # start the device->host copy of whatever the drain will
            # read FIRST (the tiny changed mask on delta shards, the
            # full outputs otherwise): a streamed completion's bytes
            # are in flight before the host ever blocks on them
            for o in (ch,) if ch is not None else outs:
                o.copy_to_host_async()
            return {
                "dev": dev_index,
                "lo": lo,
                "hi": hi,
                "outs": outs,
                "ch": ch,
            }

        def full_fetch(rec):
            dev_index = rec["dev"]
            n = rec["hi"] - rec["lo"]
            with self.probe.phase(pipeline.DEVICE_GET, device=dev_index):
                u, s, l, v = jax.device_get(rec["outs"])
            u, s, l, v = u[:n], s[:n], l[:n], v[:n]
            if self._sdc_active_for(dev_index):
                # per-chip silent corruption: only THIS chip's rows lie
                s = self._corrupt_metrics(s)
            return u, s, l, v

        def drain(rec, allow_repack=True):
            dev_index = rec["dev"]
            watch = (rec["ch"],) if rec["ch"] is not None else rec["outs"]
            try:
                # the wait window charges ONLY the completing chip —
                # never the other in-flight chips (honest utilization
                # under overlap; the r01 mode note documented the old
                # barrier's overcount)
                with self.probe.phase(
                    pipeline.STREAM_DRAIN, device=dev_index
                ):
                    if self._stream_fault is not None:
                        self._stream_fault(dev_index)
                    for o in watch:
                        o.block_until_ready()
            except Exception as e:  # noqa: BLE001 - chip failure mid-stream
                self.pool.note_complete(dev_index)
                self.num_dispatch_errors += 1
                gov = self.governor
                if (
                    not allow_repack
                    or gov is None
                    or dev_index == self._plan_probe
                ):
                    raise
                gov.record_stream_failure(dev_index, e)
                survivors = [
                    d
                    for d in self.pool.healthy_indices()
                    if d != dev_index
                ]
                if not survivors:
                    raise
                # re-pack EXACTLY this shard's row range onto the lead
                # survivor and resume the stream: no rows dropped, none
                # duplicated.  The quarantine purged the delta context,
                # so the retry always full-fetches.
                self.num_stream_repacks += 1
                redo = dispatch(
                    survivors[0], rec["lo"], rec["hi"], use_delta=False
                )
                return drain(redo, allow_repack=False)
            self.pool.note_complete(dev_index)
            n = rec["hi"] - rec["lo"]
            out = {"dev": dev_index, "lo": rec["lo"], "hi": rec["hi"]}
            if rec["ch"] is None:
                u, s, l, v = full_fetch(rec)
                out.update(
                    use=u, shortest=s, lanes=l, valid=v, rows=None
                )
                return out
            # delta shard: fetch the tiny changed mask, then move ONLY
            # the changed rows (plus host-forced churn rows) across the
            # boundary — compacted when few, full when most moved
            with self.probe.phase(pipeline.DEVICE_GET, device=dev_index):
                ch = np.asarray(jax.device_get(rec["ch"]))[:n]
            rows = np.nonzero(ch)[0]
            force = delta_ctx["force_rows"]
            if force is not None:
                local = force[
                    (force >= rec["lo"]) & (force < rec["hi"])
                ] - rec["lo"]
                if len(local):
                    rows = np.union1d(rows, local)
            self.num_delta_rows_fetched += len(rows)
            self.num_delta_rows_skipped += n - len(rows)
            if len(rows) == 0:
                out.update(
                    use=None, shortest=None, lanes=None, valid=None,
                    rows=rows,
                )
                return out
            if len(rows) > DELTA_FETCH_MAX_FRACTION * n:
                u, s, l, v = full_fetch(rec)
                out.update(
                    use=u[rows],
                    shortest=s[rows],
                    lanes=l[rows],
                    valid=v[rows],
                    rows=rows,
                )
                return out
            K = bucket_for(len(rows), ROWSEL_BUCKETS)
            idx = np.zeros(K, np.int64)
            idx[: len(rows)] = rows
            dev = self.pool.device(dev_index)
            with self.probe.phase(
                pipeline.DEVICE_SELECT, device=dev_index
            ), jit_guard.dispatch_device(dev_index):
                g = call_jit_guarded(
                    gather_selection_rows,
                    *rec["outs"],
                    jax.device_put(jnp.asarray(idx), dev),
                )
            with self.probe.phase(pipeline.DEVICE_GET, device=dev_index):
                gu, gs, gl, gv = jax.device_get(g)
            k = len(rows)
            gu, gs, gl, gv = gu[:k], gs[:k], gl[:k], gv[:k]
            if self._sdc_active_for(dev_index):
                gs = self._corrupt_metrics(gs)
            out.update(use=gu, shortest=gs, lanes=gl, valid=gv, rows=rows)
            return out

        self.num_stream_builds += 1
        clean_outs: Dict[tuple, tuple] = {}
        repacks_before = self.num_stream_repacks
        pending: List[dict] = []
        for dev_index, lo, hi in plan:
            # double-buffer slot gate: drain this chip's oldest work
            # before queueing more than STREAM_SLOTS dispatches on it
            while self.pool.inflight(dev_index) >= STREAM_SLOTS:
                sel = next(
                    j
                    for j, r in enumerate(pending)
                    if r["dev"] == dev_index
                )
                yield drain(pending.pop(sel))
            pending.append(
                dispatch(dev_index, lo, hi, delta_ctx is not None)
            )
        while pending:
            # completion order: drain any shard that is already done;
            # only when none are ready block on the oldest dispatch
            if self._stream_pick is not None:
                sel = self._stream_pick(pending)
            else:
                sel = 0
                for j, r in enumerate(pending):
                    if all(
                        o.is_ready()
                        for o in (
                            (r["ch"],) if r["ch"] is not None else r["outs"]
                        )
                    ):
                        sel = j
                        break
            rec = pending.pop(sel)
            key = (rec["dev"], rec["lo"], rec["hi"])
            outs = rec["outs"]
            drained = drain(rec)
            if self.num_stream_repacks == repacks_before:
                # device-resident outputs retained as the NEXT build's
                # delta base (only on clean streams: a mid-stream
                # quarantine already purged residency as suspect)
                clean_outs[key] = outs
            yield drained
        if self.num_stream_repacks == repacks_before:
            self._stream_outs = clean_outs
        else:
            self._stream_outs = None

    # -- device build ------------------------------------------------------

    def _select_rows_gathered(
        self,
        rows,
        tables,
        dv,
        per_area,
        table,
        enc,
        area_link_states,
        prefix_state,
    ):
        """Gather the given candidate-table rows into a padded [K, C]
        batch, run the selection kernel as ONE committed dispatch (the
        pool's lead healthy chip, or the armed probe chip), decode, and
        return ``(results, inc_dev)``.  Shared by the prefix-churn
        incremental path and the warm-selective generation-delta path —
        both re-select only the rows that can have moved."""
        import jax
        import jax.numpy as jnp

        from openr_tpu.ops import jit_guard
        from openr_tpu.ops.csr import bucket_for
        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.ops.route_select import multi_area_select_from_tables
        from openr_tpu.tracing import pipeline

        dist, nh, ovl, soft = tables
        inc_dev = None
        # selective gathers ride ONE chip: the pool's lead healthy
        # device, or the armed probe chip (a quarantined chip earning
        # its way back must exercise real work, and its output is
        # shadow-verified before anything is served)
        if self._use_pool():
            devices, probe = self._dispatch_device_set()
            inc_dev = probe if probe is not None else devices[0]
            if self.governor is not None:
                self.governor.confirm_plan([inc_dev])
        K = bucket_for(len(rows), ROWSEL_BUCKETS)
        # gather changed rows into a padded [K, C] batch; padding
        # repeats row 0 with cand_ok forced off
        with self.probe.phase(pipeline.PAD_PACK):
            ridx = np.zeros(K, np.int64)
            ridx[: len(rows)] = rows
            g_ok = dv.cand_ok[ridx]
            g_ok[len(rows):] = False
            gathered = (
                dv.cand_area[ridx],
                dv.cand_node[ridx],
                g_ok,
                dv.drain_metric[ridx],
                dv.path_pref[ridx],
                dv.source_pref[ridx],
                dv.distance[ridx],
                dv.cand_node_in_area[ridx],
            )
        if inc_dev is not None:
            dev = self.pool.device(inc_dev)
            t_dist, t_nh, t_ovl, t_soft = self._replicated_tables(
                inc_dev, (dist, nh, ovl, soft)
            )
            with self.probe.phase(pipeline.TRANSFER, device=inc_dev):
                args = tuple(jax.device_put(a, dev) for a in gathered)
        else:
            t_dist, t_nh, t_ovl, t_soft = dist, nh, ovl, soft
            with self.probe.phase(pipeline.TRANSFER):
                args = tuple(jnp.asarray(a) for a in gathered)
        gather_dev = inc_dev if inc_dev is not None else 0
        with self.probe.phase(
            pipeline.DEVICE_COMPUTE, device=gather_dev
        ), jit_guard.dispatch_device(
            inc_dev if inc_dev is not None else None
        ):
            use, shortest, lanes, valid = call_jit_guarded(
                multi_area_select_from_tables,
                t_dist,
                t_nh,
                t_ovl,
                t_soft,
                *args,
                per_area_distance=per_area,
            )
        if inc_dev is not None:
            self.pool.note_dispatch(inc_dev)
        with self.probe.phase(pipeline.DEVICE_GET, device=gather_dev):
            use, shortest, lanes, valid = jax.device_get(
                (use, shortest, lanes, valid)
            )
        if self._sdc_active_for(inc_dev if inc_dev is not None else 0):
            shortest = self._corrupt_metrics(shortest)
        with self.probe.phase(pipeline.DECODE):
            results = self._decode_rows(
                [(i, table.row_prefix[r]) for i, r in enumerate(rows)],
                use,
                shortest,
                lanes,
                valid,
                dv,
                np.asarray(ridx),
                enc,
                area_link_states,
                prefix_state,
            )
        return results, inc_dev

    def _delta_ctx_for(
        self, plan, D: int, enc, dv, changed_prefixes, exact_churn: bool
    ):
        """Eligibility + context for on-device delta extraction on a
        FULL build — the warm-start ``take_last_changed_prefixes``
        pattern extended to the cold path.  A row may patch through
        from the previous RouteDb only when everything its decode
        depends on is pinned: the previous build's selection outputs
        (device-resident, same shard plan), a layout-shared encoding
        chain (same symbol tables and root-out lane order), an exact
        prefix-churn delta (entry-object content the candidate columns
        don't encode — forwarding algorithm, labels — can only move
        with churn), identical static routes, no live KSP2 prefixes
        (their routes read the WHOLE topology) and no MPLS label pass.
        Probe builds decline: a probing chip must be exercised and
        attributable end to end, not vouch for 'unchanged'."""
        prev = self._prev_sel
        if (
            prev is None
            or self._last_db is None
            or not exact_churn
            or self._plan_probe is not None
            or self._ksp2_present
            or self.solver.enable_node_segment_label
        ):
            return None
        if (
            prev["degree"] != D
            or prev["shape"] != dv.cand_ok.shape
            or prev["plan"] != tuple(plan)
        ):
            return None
        prev_enc = prev["enc"]
        if prev_enc.src is not enc.src or prev_enc.areas != enc.areas:
            return None
        statics = self.solver.get_static_routes()
        snap = prev["statics"]
        if len(snap) != len(statics) or any(
            snap.get(k) is not v for k, v in statics.items()
        ):
            return None
        # drain-state deltas: decode wraps the winning entry via
        # LinkState drain lookups, so rows touching a node whose
        # overload/soft-drain state moved must re-decode even when
        # their selection outputs are identical (the kernel folds this
        # mask into its changed-row computation)
        node_changed = (prev_enc.overloaded != enc.overloaded) | (
            prev_enc.soft != enc.soft
        )
        # slot-membership churn since the delta base: renamed slots can
        # keep byte-identical selection outputs while their decoded
        # route contents (names, link objects) moved — their rows must
        # re-decode (tombstone/revive flips change dist and are caught
        # by the kernel's output diff regardless)
        for ai, t in enumerate(enc.topos):
            if t.slot_changed is not None:
                node_changed[ai] |= t.slot_changed
        force = None
        if changed_prefixes:
            rows = self._cand_table.rows_for(changed_prefixes)
            if rows:
                force = np.asarray(sorted(rows), np.int64)
        return {
            "shards": prev["shards"],
            "node_changed": node_changed,
            "force_rows": force,
        }

    def _retain_prev_sel(self, plan, D: int, enc, dv) -> bool:
        """Retain this build's device-resident selection outputs as the
        next full build's delta base.  Returns True when the stream was
        clean (no mid-stream re-pack) — also the caller's signal that
        the shard plan attribution is trustworthy."""
        outs = self._stream_outs
        self._stream_outs = None
        if outs is None or len(outs) != len(plan):
            self._prev_sel = None
            return False
        self._prev_sel = {
            "plan": tuple(plan),
            "degree": D,
            "shape": dv.cand_ok.shape,
            "shards": outs,
            "enc": enc,
            "statics": dict(self.solver.get_static_routes()),
        }
        return True

    def _warm_affected_rows(self, dv, table):
        """Candidate-table rows whose selection inputs can have moved in
        the last warm generation delta: any candidate whose (area, node)
        cell — own-area id or cross-area resolution — changed distance,
        lanes, or drain state.  Every other row provably reproduces its
        previous selection output, so the patch path skips it."""
        ch = self._warm_changed_nodes  # [A, V] bool
        row_hit = (ch[dv.cand_area, dv.cand_node] & dv.cand_ok).any(axis=1)
        cnia = dv.cand_node_in_area  # [P, C, A]
        ok3 = (cnia >= 0) & dv.cand_ok[:, :, None]
        a_idx = np.arange(ch.shape[0])[None, None, :]
        hit3 = ok3 & ch[a_idx, np.maximum(cnia, 0)]
        row_hit |= hit3.any(axis=(1, 2))
        return np.nonzero(row_hit)[0]

    def _build_device(
        self,
        area_link_states,
        prefix_state,
        changed_prefixes,
        force_full,
        delta_class=None,
    ):
        from openr_tpu.ops.csr import bucket_for
        from openr_tpu.tracing import pipeline

        me = self.solver.my_node_name
        if not any(ls.has_node(me) for ls in area_link_states.values()):
            # this tick's delta is consumed without being applied to the
            # candidate table — mark it stale or a later apply_dirty would
            # run selection over rows missing this churn
            self._last_db = None
            self._table_synced = False
            self._attr_table = None
            return None
        prev_enc = self._last_enc
        with self.probe.phase(pipeline.ENCODE):
            enc = self._encoded(area_link_states, me)
        self._last_enc = enc

        # table sync is driven ONLY by prefix churn; the build mode (patch
        # vs full selection) additionally requires an unchanged topology
        table = self._cand_table
        with self.probe.phase(pipeline.HOST_FETCH):
            # exact_churn: the table was patched from a KNOWN prefix
            # delta — the precondition for the full-build delta-decode
            # path (a full_sync may reassign rows and admits churn the
            # device's changed-row compare cannot see)
            exact_churn = (
                changed_prefixes is not None and self._table_synced
            )
            try:
                if exact_churn:
                    table.apply_dirty(prefix_state, changed_prefixes)
                else:
                    table.full_sync(prefix_state)
            except ValueError:
                self.num_fallback_cand_overflow += 1
                raise
            self._table_synced = True
            dv = table.derived(enc)

        incremental = (
            changed_prefixes is not None
            and not force_full
            and self._last_db is not None
            and prev_enc is enc
            and len(changed_prefixes) <= ROWSEL_BUCKETS[-1]
        )

        D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
        per_area = (
            self.solver.route_selection_algorithm
            == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
        )
        # patch-path eligibility must be judged against the PRE-build
        # RouteDb base (warm-selective needs _last_db built on prev_enc)
        patch_base = self._last_db
        dist, nh, ovl, soft = self._spf(enc, D, delta_class=delta_class)

        if incremental:
            rows = table.rows_for(changed_prefixes)
            deleted = [
                p for p in changed_prefixes if p not in table.pid
            ]
            if not rows and not deleted:
                self.num_incremental_builds += 1
                # nothing freshly computed this tick: a sampled shadow
                # check on this db must not attribute stale rows
                self._attr_table = None
                return self._last_db
            results: Dict[str, Optional[RibUnicastEntry]] = {
                p: None for p in deleted
            }
            inc_dev = None
            if rows:
                # deleted-only ticks dispatch nothing, so they must not
                # arm a probe a build would never exercise — the helper
                # (which arms at most one) only runs when rows exist
                gathered_results, inc_dev = self._select_rows_gathered(
                    rows,
                    (dist, nh, ovl, soft),
                    dv,
                    per_area,
                    table,
                    enc,
                    area_link_states,
                    prefix_state,
                )
                results.update(gathered_results)
            self.num_incremental_builds += 1
            self.num_device_builds += 1
            if inc_dev is not None and rows:
                self._attr_rows = {int(r): inc_dev for r in rows}
                self._attr_plan = None
                self._attr_table = table
            else:
                self._attr_table = None
            # a patched build leaves the resident full-table outputs
            # stale for its rows — they can no longer vouch for the
            # next full build's delta
            self._prev_sel = None
            with self.probe.phase(pipeline.DELTA_EXTRACT):
                return _patch_route_db(
                    self._last_db, results, self.solver.get_static_routes()
                )

        # ---- warm-selective rebuild (generation-delta topology tick) -----
        # the warm solve already re-relaxed only the perturbed frontier;
        # the changed-node diff now bounds which candidate rows can have
        # moved, and everything else patches through from the previous
        # RouteDb — selection, decode and the publication diff all stay
        # O(perturbation), not O(total prefixes)
        if (
            self._warm_solved
            and self._warm_changed_nodes is not None
            and patch_base is not None
            and prev_enc is self._warm_base_enc
            and self._table_synced
            and not self._ksp2_present
            and not self.solver.enable_node_segment_label
        ):
            with self.probe.phase(pipeline.WARM_PLAN):
                affected = self._warm_affected_rows(dv, table)
                churn_rows = (
                    table.rows_for(changed_prefixes)
                    if changed_prefixes
                    else []
                )
                deleted = [
                    p
                    for p in (changed_prefixes or ())
                    if p not in table.pid
                ]
                sel_rows = sorted(set(affected.tolist()) | set(churn_rows))
            if len(sel_rows) <= ROWSEL_BUCKETS[-1]:
                results = {p: None for p in deleted}
                inc_dev = None
                if sel_rows:
                    gathered_results, inc_dev = self._select_rows_gathered(
                        sel_rows,
                        (dist, nh, ovl, soft),
                        dv,
                        per_area,
                        table,
                        enc,
                        area_link_states,
                        prefix_state,
                    )
                    results.update(gathered_results)
                self.num_warm_selective_builds += 1
                self.num_device_builds += 1
                if inc_dev is not None and sel_rows:
                    self._attr_rows = {int(r): inc_dev for r in sel_rows}
                    self._attr_plan = None
                    self._attr_table = table
                else:
                    self._attr_table = None
                changed_out = {
                    table.row_prefix[r]
                    for r in sel_rows
                    if table.row_prefix[r] is not None
                }
                changed_out.update(deleted)
                self._last_changed_prefixes = changed_out
                self._prev_sel = None  # patched build: outputs stale
                with self.probe.phase(pipeline.DELTA_EXTRACT):
                    return _patch_route_db(
                        patch_base,
                        results,
                        self.solver.get_static_routes(),
                    )

        # ---- full build (streamed pipeline, ISSUE 11) --------------------
        # the selection batch shards row-contiguously across the pool's
        # healthy chips (one shard on the lead chip for single-chip
        # pools), every shard a committed per-device dispatch so a wrong
        # row is attributable to exactly one device; shards drain as
        # STREAMED completions — decode of shard N overlaps the solve of
        # the shards still in flight instead of waiting on a fetch
        # barrier
        n_active = (max(table.pid.values()) + 1) if table.pid else 0
        plan = self._plan_full_dispatch(dv.cand_ok.shape[0], n_active)
        delta_ctx = self._delta_ctx_for(
            plan, D, enc, dv, changed_prefixes, exact_churn
        )
        if delta_ctx is not None:
            deleted = [
                p
                for p in (changed_prefixes or ())
                if p not in table.pid
            ]
            results = {p: None for p in deleted}
            decoded_rows: List[int] = []
            shard_devs: Dict[int, int] = {}
            for shard in self._stream_row_shards(
                dv, (dist, nh, ovl, soft), per_area, plan, delta_ctx
            ):
                rows = shard["rows"]
                if rows is None or not len(rows):
                    continue
                global_rows = rows + shard["lo"]
                with self.probe.phase(pipeline.DECODE):
                    row_items = [
                        (i, table.row_prefix[r])
                        for i, r in enumerate(global_rows)
                        if table.row_prefix[r] is not None
                    ]
                    results.update(
                        self._decode_rows(
                            row_items,
                            shard["use"],
                            shard["shortest"],
                            shard["lanes"],
                            shard["valid"],
                            dv,
                            global_rows,
                            enc,
                            area_link_states,
                            prefix_state,
                        )
                    )
                for r in global_rows:
                    shard_devs[int(r)] = shard["dev"]
                decoded_rows.extend(int(r) for r in global_rows)
            self.num_device_builds += 1
            self.num_delta_builds += 1
            clean = self._retain_prev_sel(plan, D, enc, dv)
            if self._use_pool() and decoded_rows and clean:
                self._attr_rows = shard_devs
                self._attr_plan = None
                self._attr_table = table
            else:
                self._attr_table = None
            changed_out = {
                table.row_prefix[r]
                for r in decoded_rows
                if table.row_prefix[r] is not None
            }
            changed_out.update(deleted)
            self._last_changed_prefixes = changed_out
            with self.probe.phase(pipeline.DELTA_EXTRACT):
                return _patch_route_db(
                    patch_base, results, self.solver.get_static_routes()
                )

        # a full decode re-derives KSP2 presence from scratch (the
        # warm-selective patch path declines while any KSP2 prefix is
        # live, and _decode_rows re-raises the flag on discovery)
        self._ksp2_present = False
        results = {}
        for shard in self._stream_row_shards(
            dv, (dist, nh, ovl, soft), per_area, plan, None
        ):
            with self.probe.phase(pipeline.DECODE):
                use = shard["use"]
                lo = shard["lo"]
                # only rows with at least one selection winner produce
                # routes; decode runs per shard, overlapping the solves
                # still in flight
                local_winners = np.nonzero(use.any(axis=1))[0]
                row_items = []
                for i in local_winners:
                    p = table.row_prefix[lo + int(i)]
                    if p is not None:
                        row_items.append((int(i), p))
                results.update(
                    self._decode_rows(
                        row_items,
                        use,
                        shard["shortest"],
                        shard["lanes"],
                        shard["valid"],
                        dv,
                        np.arange(lo, shard["hi"]),
                        enc,
                        area_link_states,
                        prefix_state,
                    )
                )
        self.num_device_builds += 1
        clean = self._retain_prev_sel(plan, D, enc, dv)
        if self._use_pool() and clean:
            self._attr_plan = plan
            self._attr_rows = None
            self._attr_table = table
        else:
            # single-chip pool, or a mid-stream re-pack moved rows off
            # the planned chips: don't attribute what the plan no
            # longer describes
            self._attr_table = None

        with self.probe.phase(pipeline.DECODE):
            route_db = DecisionRouteDb()
            for prefix, entry in results.items():
                if entry is not None:
                    route_db.add_unicast_route(entry)
            # static-route overlay + MPLS labels: scalar (small)
            for prefix, sentry in self.solver.get_static_routes().items():
                if prefix not in route_db.unicast_routes:
                    route_db.add_unicast_route(sentry)
            if self.solver.enable_node_segment_label:
                self.solver._build_node_label_routes(
                    area_link_states, route_db
                )
        return route_db

    @staticmethod
    def _corrupt_metrics(shortest):
        """The tpu_corrupt perturbation: shift every finite per-area
        shortest-path metric by a constant.  Plausible (routes stay
        loop-free and reachable, so FIBs never blackhole) yet provably
        wrong — exactly the corruption class only a RIB diff against the
        scalar oracle can catch.  Deterministic: no randomness, so a
        seeded chaos run replays byte-identically."""
        out = np.array(shortest, copy=True)
        finite = np.isfinite(out)
        out[finite] += 7.0
        return out

    # -- decode ------------------------------------------------------------

    def _decode_rows(
        self,
        row_items: List[Tuple[int, str]],
        use,  # [R', C] (R' = gathered batch or full cap)
        shortest,  # [R', A]
        lanes,  # [R', A, D]
        valid,  # [R', A]
        dv,
        gather_rows: Optional[np.ndarray],  # None = row index == table row
        enc,
        area_link_states,
        prefix_state,
    ) -> Dict[str, Optional[RibUnicastEntry]]:
        """Decode device outputs for the given (result_index, prefix)
        pairs.  When ``gather_rows`` is set, candidate-table columns are
        indexed by gather_rows[i]; device outputs always by i.

        The per-route loop is the host-side tail of every full build, so
        everything per-winner is vectorized up front (one object-array
        fancy-index resolves every winner name; one ufunc.at pass each
        computes the skip-if-self and min-nexthop gates) and the ECMP
        memo is keyed by the row's raw bytes instead of per-element
        tuples — at DecisionBenchmark's 100k-prefix scale this decode
        was the difference between losing and beating the scalar
        backend on initial full builds (VERDICT r3 weak #3)."""
        me = self.solver.my_node_name
        all_entries = prefix_state.prefixes()
        out_edges_by_area = [t.root_out_edges(me) for t in enc.topos]
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop

        R = use.shape[0]
        u_rows, u_cols = np.nonzero(use)
        u_starts_l = np.searchsorted(u_rows, np.arange(R + 1)).tolist()
        ti_w = gather_rows[u_rows] if gather_rows is not None else u_rows
        ai_w = dv.cand_area[ti_w, u_cols]
        nid_w = dv.cand_node[ti_w, u_cols]
        # winner names via one object-array fancy index (per-winner dict
        # lookups through id_to_node were ~40% of decode time)
        num_areas = len(enc.topos)
        max_v = max((len(t.id_to_node) for t in enc.topos), default=1)
        name_lut = np.full((num_areas, max(max_v, 1)), None, dtype=object)
        for ai, t in enumerate(enc.topos):
            name_lut[ai, : len(t.id_to_node)] = t.id_to_node
        names_obj = name_lut[ai_w, nid_w]  # [W] object
        names_w = names_obj.tolist()
        areas_w = [enc.areas[a] for a in ai_w.tolist()]
        # vectorized row gates: any-winner-is-self, min-nexthop req
        # (max over winners of the candidate column, addBestPaths
        # SpfSolver.cpp:596-620; unset is encoded 0 and never gates)
        self_any = np.zeros(R, bool)
        req = np.zeros(R, np.int64)
        if len(u_rows):
            np.logical_or.at(self_any, u_rows, names_obj == me)
            np.maximum.at(req, u_rows, dv.min_nexthop[ti_w, u_cols])
        self_l = self_any.tolist()
        req_l = req.tolist()
        # ECMP/metric memo keyed by the row's raw bytes: many prefixes
        # share one advertiser, and their nexthop set + igp metric are
        # fully determined by (v4ness, lane bits, per-area validity and
        # metric) — one contiguous-bytes key replaces per-element tuples
        lanes_u8 = np.ascontiguousarray(
            lanes.reshape(R, -1), dtype=np.uint8
        )
        comp = np.concatenate(
            [
                lanes_u8,
                valid.astype(np.uint8),
                np.ascontiguousarray(shortest, dtype=np.float32)
                .view(np.uint8)
                .reshape(R, -1),
            ],
            axis=1,
        )
        nh_memo: Dict[tuple, Optional[tuple]] = {}
        drain_cache: Dict[Tuple[str, str], bool] = {}

        results: Dict[str, Optional[RibUnicastEntry]] = {}
        # KSP2 prefixes are classified by the forwarding algorithm of the
        # MIN selection winner (SpfSolver.cpp:247-250), deferred until
        # every area's k-path memo is seeded as one device batch
        ksp2_prefixes: List[str] = []
        ksp2_dests: Dict[str, list] = {}
        for i, prefix in row_items:
            c0 = u_starts_l[i]
            c1 = u_starts_l[i + 1]
            if c0 == c1:
                results[prefix] = None
                continue
            if c1 - c0 == 1:
                best = (names_w[c0], areas_w[c0])
            else:
                best = min(
                    (names_w[k], areas_w[k]) for k in range(c0, c1)
                )
            entries = all_entries[prefix]
            if (
                entries[best].forwarding_algorithm
                == PrefixForwardingAlgorithm.KSP2_ED_ECMP
            ):
                ksp2_prefixes.append(prefix)
                for k in sorted(
                    range(c0, c1), key=lambda k: (names_w[k], areas_w[k])
                ):
                    ksp2_dests.setdefault(areas_w[k], []).append(
                        names_w[k]
                    )
                continue
            is_v4 = prefix_is_v4(prefix)
            if is_v4 and not v4_ok:
                results[prefix] = None
                continue
            if self_l[i]:
                results[prefix] = None  # skip-if-self (SpfSolver.cpp:253)
                continue
            key = (comp[i].tobytes(), is_v4)
            cached = nh_memo.get(key, False)
            if cached is False:
                cached = self._merged_nexthops(
                    is_v4, lanes[i], valid[i], shortest[i],
                    out_edges_by_area,
                )
                nh_memo[key] = cached
            if cached is None:
                results[prefix] = None
                continue
            total_next_hops, shortest_metric = cached
            if req_l[i] > len(total_next_hops):
                results[prefix] = None
                continue
            best_entry = entries.get(best)
            if best_entry is None:
                results[prefix] = None
                continue
            dr = drain_cache.get(best)
            if dr is None:
                dr = self.solver._is_node_drained(best, area_link_states)
                drain_cache[best] = dr
            entry = drained_entry(best_entry) if dr else best_entry
            local_considered = any(n == me for (n, _a) in entries.keys())
            results[prefix] = RibUnicastEntry(
                prefix=prefix,
                nexthops=total_next_hops,
                best_prefix_entry=entry,
                best_area=best[1],
                igp_cost=shortest_metric,
                local_prefix_considered=local_considered,
            )
        if ksp2_prefixes:
            self._ksp2_present = True
            for a, dests in sorted(ksp2_dests.items()):
                ai = enc.area_index(a)
                self._ksp2_engine(
                    a, area_link_states[a], enc.topos[ai]
                ).seed(dests)
            for prefix in ksp2_prefixes:
                # scalar KSP2 chain over the device-seeded k-path memo —
                # no host Dijkstra runs (decision/ksp2.py)
                results[prefix] = self.solver.create_route_for_prefix(
                    prefix, area_link_states, prefix_state
                )
        return results

    def _merged_nexthops(
        self,
        is_v4,
        lanes_row,  # [A, D] for this row
        valid_row,  # [A]
        shortest_row,  # [A]
        out_edges_by_area,
    ) -> Optional[tuple]:
        """Per-area lane decode + cross-area min-metric nexthop merge
        (SpfSolver.cpp:276-302) for one distinct route signature; the
        caller memoizes the result.  Returns (frozen nexthop set, igp
        metric) or None when no usable nexthops survive."""
        me = self.solver.my_node_name
        shortest_metric = INF
        total_next_hops: set = set()
        a_idx, l_idx = np.nonzero(lanes_row)
        by_area: Dict[int, list] = {}
        for ai, lane in zip(a_idx.tolist(), l_idx.tolist()):
            by_area.setdefault(ai, []).append(lane)
        for ai, lanes_hit in by_area.items():
            if not valid_row[ai]:
                continue
            m = float(shortest_row[ai])
            out_edges = out_edges_by_area[ai]
            nhs = set()
            for lane in lanes_hit:
                if lane >= len(out_edges):
                    continue
                link, neighbor = out_edges[lane]
                nhs.add(
                    NextHop(
                        address=(
                            link.get_nh_v4_from_node(me)
                            if is_v4
                            and not self.solver.v4_over_v6_nexthop
                            else link.get_nh_v6_from_node(me)
                        ),
                        if_name=link.get_iface_from_node(me),
                        metric=int(m),
                        area=link.area,
                        neighbor_node_name=neighbor,
                    )
                )
            if not nhs:
                continue
            if shortest_metric >= m:
                if shortest_metric > m:
                    shortest_metric = m
                    total_next_hops.clear()
                total_next_hops |= nhs
        # the memoized value is handed to MANY RibUnicastEntry objects;
        # freeze it so no later in-place mutation of one route's
        # nexthops can corrupt its siblings (ADVICE r3)
        if not total_next_hops:
            return None
        return frozenset(total_next_hops), shortest_metric
