"""Decision compute backends: scalar (host) and TPU (batched kernels).

The backend seam is exactly the reference's pure-compute boundary
(SpfSolver takes LinkState/PrefixState in, RouteDb out, SpfSolver.h:136).
`ScalarBackend` wraps the oracle SpfSolver.  `TpuBackend` runs the fused
``spf_and_select`` kernel for SP_ECMP selection and decodes device
outputs back into RibUnicastEntries; KSP2_ED_ECMP prefixes run their
masked re-solve fan-out as a second batched device call
(decision/ksp2.py) with only the greedy path trace + label-stack
assembly on the host.  Static routes and MPLS label routes stay scalar
(O(nodes), no per-prefix fan-out).  Both backends must produce identical
RouteDbs — enforced by differential tests.
"""

from __future__ import annotations

import copy
import ipaddress
from typing import Dict, Optional

import numpy as np

from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, RibUnicastEntry
from openr_tpu.decision.spf_solver import SpfSolver, select_best_node_area
from openr_tpu.types import (
    NextHop,
    PrefixForwardingAlgorithm,
    RouteComputationRules,
)


class DecisionBackend:
    def build_route_db(
        self,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        raise NotImplementedError


class ScalarBackend(DecisionBackend):
    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver

    def build_route_db(self, area_link_states, prefix_state):
        return self.solver.build_route_db(area_link_states, prefix_state)


class TpuBackend(DecisionBackend):
    """Device-accelerated buildRouteDb.

    Topology and candidate tables are padded to buckets so the jit cache
    stays warm across LSDB churn (SURVEY §7 hard-part 4).
    """

    def __init__(
        self,
        solver: SpfSolver,
        node_buckets=(16, 64, 256, 1024, 4096),
        cand_buckets=(8, 16, 32, 64),
    ) -> None:
        self.solver = solver  # scalar fallback + MPLS/static
        self.node_buckets = tuple(node_buckets)
        self.cand_buckets = tuple(cand_buckets)
        self.num_device_builds = 0
        self.num_scalar_builds = 0
        #: scalar fallbacks caused specifically by a prefix advertised by
        #: more candidates than the largest candidate bucket (VERDICT r1
        #: weak #8: the cause must be distinguishable)
        self.num_fallback_cand_overflow = 0
        #: EncodedTopology cache keyed by (area, LinkState.topology_seq):
        #: most rebuilds are prefix churn on an unchanged graph, and
        #: re-encoding a 4096-node LSDB costs tens of ms of the debounce
        #: budget (SURVEY §7 hard-part 4)
        self._topo_cache: dict = {}
        #: Ksp2DeviceEngine per (area, topology_seq) — the traced-path memo
        #: itself lives in the LinkState; this only avoids rebuilding the
        #: link-id table every rebuild
        self._ksp2_engines: dict = {}
        self.num_encode_hits = 0
        self.num_encodes = 0

    def build_route_db(self, area_link_states, prefix_state):
        # the device kernel implements the default selection semantics
        # (enabled best-route selection, SHORTEST_DISTANCE); anything else —
        # and multi-area, where selection is global across areas — goes
        # through the scalar oracle for exactness
        if (
            len(area_link_states) != 1
            or not self.solver.enable_best_route_selection
            or self.solver.route_selection_algorithm
            != RouteComputationRules.SHORTEST_DISTANCE
        ):
            self.num_scalar_builds += 1
            return self.solver.build_route_db(area_link_states, prefix_state)
        try:
            return self._build_single_area(area_link_states, prefix_state)
        except ValueError:
            # e.g. a prefix with more candidates than the device bucket —
            # fall back rather than wedging the rebuild loop
            self.num_scalar_builds += 1
            return self.solver.build_route_db(area_link_states, prefix_state)

    def _build_single_area(self, area_link_states, prefix_state):
        import jax.numpy as jnp

        from openr_tpu.ops.csr import encode_link_state, encode_prefix_candidates
        from openr_tpu.ops.route_select import spf_and_select

        (area, link_state), = area_link_states.items()
        me = self.solver.my_node_name
        if not link_state.has_node(me):
            return None

        # the cache value pins the LinkState object itself: identity must be
        # compared via a held reference (a bare id() could be reused by a
        # replacement object after GC and serve stale arrays)
        cache_key = (area, link_state.topology_seq)
        cached = self._topo_cache.get(cache_key)
        if cached is not None and cached[0] is link_state:
            topo = cached[1]
            self.num_encode_hits += 1
        else:
            topo = encode_link_state(link_state, node_buckets=self.node_buckets)
            self._topo_cache = {cache_key: (link_state, topo)}
            self._ksp2_engines = {}
            self.num_encodes += 1
        if me not in topo.node_ids:
            return None
        try:
            cands = encode_prefix_candidates(
                prefix_state, topo, area, cand_buckets=self.cand_buckets
            )
        except ValueError:
            self.num_fallback_cand_overflow += 1
            raise
        prefixes = cands.prefixes

        D = max(topo.max_out_degree(), 1)
        valid, metric, nh_out, num_nh, winners = spf_and_select(
            jnp.asarray(topo.src),
            jnp.asarray(topo.dst),
            jnp.asarray(topo.w),
            jnp.asarray(topo.edge_ok),
            jnp.ones((1, topo.padded_edges), bool),
            jnp.asarray(topo.overloaded)[None],
            jnp.asarray(topo.soft)[None],
            jnp.asarray([topo.node_id(me)], jnp.int32),
            jnp.asarray(cands.cand_node),
            jnp.asarray(cands.cand_ok),
            jnp.asarray(cands.drain_metric),
            jnp.asarray(cands.path_pref),
            jnp.asarray(cands.source_pref),
            jnp.asarray(cands.distance),
            jnp.asarray(cands.min_nexthop),
            max_degree=D,
        )
        self.num_device_builds += 1
        # ONE device->host fetch for all outputs: over a tunneled TPU each
        # transfer is a full round trip, and four separate np.asarray calls
        # cost ~4x one device_get (measured ~256ms vs ~69ms on v5e/axon) —
        # that difference alone would blow the 10-250ms debounce budget
        import jax

        valid, metric, nh_out, winners = (
            a[0] for a in jax.device_get((valid, metric, nh_out, winners))
        )

        out_edges = topo.root_out_edges(me)
        route_db = DecisionRouteDb()
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop
        all_entries = prefix_state.prefixes()

        # classify by the forwarding algorithm of the MIN selection winner
        # (SpfSolver.cpp:247-250: algorithm comes from the best entry of
        # allNodeAreas, not from "any advertiser") using the device winner
        # sets, then run the KSP2 masked re-solves as one device batch
        winner_sets = [
            self._winner_set(p, winners, cands, topo, area)
            for p in range(len(prefixes))
        ]
        ksp2_prefixes = set()
        ksp2_dests = []
        for p, prefix in enumerate(prefixes):
            wset = winner_sets[p]
            if not wset:
                continue
            fa = all_entries[prefix][min(wset)].forwarding_algorithm
            if fa == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
                ksp2_prefixes.add(prefix)
                ksp2_dests.extend(node for (node, _a) in sorted(wset))

        if ksp2_prefixes:
            self._ksp2_engine(area, link_state, topo).seed(ksp2_dests)

        for p, prefix in enumerate(prefixes):
            if prefix in ksp2_prefixes:
                # scalar KSP2 chain over the device-seeded k-path memo —
                # no host Dijkstra runs (decision/ksp2.py)
                entry = self.solver.create_route_for_prefix(
                    prefix, area_link_states, prefix_state
                )
                if entry is not None:
                    route_db.add_unicast_route(entry)
                continue
            if ipaddress.ip_network(prefix).version == 4 and not v4_ok:
                continue
            if not valid[p]:
                continue
            entry = self._decode_route(
                prefix,
                p,
                metric,
                nh_out,
                winner_sets[p],
                out_edges,
                area,
                link_state,
                prefix_state,
            )
            if entry is not None:
                route_db.add_unicast_route(entry)

        # static-route overlay + MPLS labels: scalar (small)
        for prefix, sentry in self.solver.get_static_routes().items():
            if prefix not in route_db.unicast_routes:
                route_db.add_unicast_route(sentry)
        if self.solver.enable_node_segment_label:
            self.solver._build_node_label_routes(area_link_states, route_db)
        return route_db

    @staticmethod
    def _winner_set(p, winners, cands, topo, area):
        out = set()
        for c in range(cands.cand_node.shape[1]):
            if winners[p, c]:
                out.add((topo.id_to_node[int(cands.cand_node[p, c])], area))
        return out

    def _ksp2_engine(self, area, link_state, topo):
        from openr_tpu.decision.ksp2 import Ksp2DeviceEngine

        key = (area, link_state.topology_seq)
        eng = self._ksp2_engines.get(key)
        if eng is None or eng.link_state is not link_state or eng.topo is not topo:
            eng = Ksp2DeviceEngine(link_state, topo, self.solver.my_node_name)
            self._ksp2_engines = {key: eng}
        return eng

    def _decode_route(
        self,
        prefix,
        p,
        metric,
        nh_out,
        all_node_areas,  # device winner (node, area) set for this prefix
        out_edges,
        area,
        link_state,
        prefix_state,
    ) -> Optional[RibUnicastEntry]:
        me = self.solver.my_node_name
        entries = prefix_state.prefixes().get(prefix, {})
        if not all_node_areas:
            return None
        best_node_area = select_best_node_area(all_node_areas, me)
        best = entries.get(best_node_area)
        if best is None:
            return None
        is_v4 = ipaddress.ip_network(prefix).version == 4
        nexthops = set()
        igp = float(metric[p])
        for lane, (link, neighbor) in enumerate(out_edges):
            if lane >= nh_out.shape[1] or not nh_out[p, lane]:
                continue
            nexthops.add(
                NextHop(
                    address=(
                        link.get_nh_v4_from_node(me)
                        if is_v4 and not self.solver.v4_over_v6_nexthop
                        else link.get_nh_v6_from_node(me)
                    ),
                    if_name=link.get_iface_from_node(me),
                    metric=int(igp),
                    area=link.area,
                    neighbor_node_name=neighbor,
                )
            )
        if not nexthops:
            return None
        entry = copy.deepcopy(best)
        if self.solver._is_node_drained(best_node_area, {area: link_state}):
            entry.metrics = type(entry.metrics)(
                version=entry.metrics.version,
                drain_metric=1,
                path_preference=entry.metrics.path_preference,
                source_preference=entry.metrics.source_preference,
                distance=entry.metrics.distance,
            )
        local_considered = any(n == me for (n, _a) in entries.keys())
        return RibUnicastEntry(
            prefix=prefix,
            nexthops=nexthops,
            best_prefix_entry=entry,
            best_area=best_node_area[1],
            igp_cost=igp,
            local_prefix_considered=local_considered,
        )
