"""Operator-facing what-if: 'which of MY routes change if link X fails?'

Wires the flagship sweep engine (ops/whatif.py + ops/sweep_select.py)
into the daemon: the ctrl call takes a list of candidate link failures,
runs them as one device batch against the CURRENT LSDB from this node's
vantage, and returns per-failure route deltas (removed / rerouted /
metric-changed) decoded to neighbor names.  The engine (base solve +
repair plan + selection tables) is cached per LSDB change generation,
so an operator sweeping many links pays the setup once.

Three device engines cover the accelerated configurations:

  * ``WhatIfApiEngine`` — single-area vantage over the warm-start
    repair sweep + on-device selection (the fastest path).
  * ``MultiAreaWhatIfEngine`` — multi-area LSDBs over the fleet-family
    kernel (ops.fleet_tables.whatif_multi_area_tables): per snapshot
    the failed SET of links (singles, parallel bundles, simultaneous
    maintenance windows) is masked in each member's area, selection is
    global, and the cross-area min-metric merge happens in the host
    decode — the same semantics the reference reaches scalar via
    getDecisionRouteDb (Decision.cpp:342).
  * ``DeviceBuildWhatIfEngine`` — KSP2_ED_ECMP vantages / exotic
    selection rules: full DEVICE builds (tables + the device KSP2
    engine) minus the links, diffed.

Only scalar-only deployments outside the native engine's reach answer
through ``GenericSolverWhatIfEngine``: a full scalar-solver build with
the links actually removed — slow but jax-free and algorithm-complete,
so every configuration the daemon can run gets a what-if answer.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.types import prefix_is_v4

#: failure-batch buckets for the multi-area kernel (jit shapes stay
#: cache-stable across operator query sizes; chosen strictly GREATER
#: than the failure count so at least one -1 pad row exists — that row
#: doubles as the unperturbed base snapshot)
FAILURE_BUCKETS = (4, 16, 64, 256)


def resolve_pair_failures(pair_links: Dict, link_failures,
                          allow_parallel: bool = False):
    """Resolve (n1, n2) pairs against a pair→links map.  Returns
    (values, errors), one entry per failure; errors[i] is None or a
    ready-to-emit error row.  Without ``allow_parallel`` values[i] is
    the unique link value or None (pairs with multiple links error —
    engines without set solves would mislead by failing just one).
    With ``allow_parallel`` values[i] is ALWAYS a tuple of every link
    between the pair (1-tuple for a unique link): the engine fails the
    whole bundle as one simultaneous set.  Shared by every what-if
    engine so their operator-facing semantics cannot drift."""
    values, errors = [], []
    for n1, n2 in link_failures:
        hits = pair_links.get(frozenset((n1, n2)), [])
        if not hits:
            values.append(None)
            errors.append({"link": [n1, n2], "error": "unknown link"})
        elif allow_parallel:
            values.append(tuple(hits))
            errors.append(None)
        elif len(hits) == 1:
            values.append(hits[0])
            errors.append(None)
        else:
            # engines without set solves reject parallel pairs
            values.append(None)
            errors.append(
                {
                    "link": [n1, n2],
                    "error": (
                        f"{len(hits)} parallel links between pair; "
                        "single-link what-if would shift traffic to "
                        "the survivors — not supported by this engine"
                    ),
                }
            )
    return values, errors


def build_pair_links(links, area_index=None) -> Dict:
    """(n1, n2) → list of link values: plain link ids, or
    (area_index, link_id) pairs when ``area_index`` is given.  One
    builder for every what-if engine so link-identity handling cannot
    drift between them."""
    out: Dict[frozenset, list] = {}
    for i, link in enumerate(links):
        val = i if area_index is None else (area_index, i)
        out.setdefault(frozenset((link.n1, link.n2)), []).append(val)
    return out


def lane_names_for(topo, root: str) -> List[str]:
    """Lane rank → neighbor name for decoding first-hop lane rows."""
    return [nbr for (_link, nbr) in topo.root_out_edges(root)]


def decode_lane_names(lane_names: List[str], row) -> List[str]:
    return [
        lane_names[i]
        for i in np.nonzero(row)[0]
        if i < len(lane_names)
    ]


def change_kind(was: bool, now: bool) -> str:
    if was and not now:
        return "removed"
    if now and not was:
        return "added"
    return "rerouted"


class WhatIfApiEngine:
    """Cached sweep→routes pipeline for one node's vantage."""

    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver
        self._cache_key = None
        self._sweep = None
        self._selector = None
        self._topo = None
        self._prefixes: List[str] = []
        self.num_engine_builds = 0
        self.num_sweeps = 0

    def _engine_for(self, area_link_states, prefix_state, change_seq):
        from openr_tpu.ops.csr import encode_link_state, encode_prefix_candidates
        from openr_tpu.ops.sweep_select import SweepRouteSelector
        from openr_tpu.ops.whatif import LinkFailureSweep

        (area, ls), = area_link_states.items()
        key = (area, ls.topology_seq, change_seq)
        if self._cache_key == key:
            return
        topo = encode_link_state(ls)
        me = self.solver.my_node_name
        # EncodedPrefixCandidates exposes the exact candidate-array schema
        # the selector reads — no copy
        cands = encode_prefix_candidates(prefix_state, topo, area)
        sweep = LinkFailureSweep(topo, me)
        # the first what-if after an LSDB change used to pay a full cold
        # base solve; seed it from the previous generation instead (only
        # removal-affected vertices re-converge — exact, VERDICT r3
        # weak #7)
        sweep.seed_base_from(self._sweep)
        self._sweep = sweep
        self._selector = SweepRouteSelector(topo, me, cands, max_degree=sweep.D)
        self._topo = topo
        self._prefixes = cands.prefixes
        #: node-pair -> undirected link ids (PARALLEL links are distinct:
        #: link identity includes interfaces, link_state.py)
        self._pair_links = build_pair_links(topo.links)
        self._cache_key = key
        self.num_engine_builds += 1

    def run(
        self,
        link_failures: List[Tuple[str, str]],
        area_link_states,
        prefix_state,
        change_seq: int,
        simultaneous: bool = False,
    ) -> Dict:
        """One device sweep over the given candidate failures; returns
        per-failure route deltas from this node's vantage.  With
        ``simultaneous`` ALL listed links fail at once (one combined
        failure entry — maintenance-window analysis over
        LinkFailureSweep.run_sets)."""
        self._engine_for(area_link_states, prefix_state, change_seq)
        me = self.solver.my_node_name
        lane_names = lane_names_for(self._topo, me)
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop

        # allow_parallel returns every resolved failure as a tuple of
        # link ids (a bundle fails as one simultaneous set via run_sets)
        lid_sets, errors = resolve_pair_failures(
            self._pair_links, link_failures, allow_parallel=True
        )

        def lanes_to_names(lane_row) -> List[str]:
            return decode_lane_names(lane_names, lane_row)

        def changes_from_row(deltas, row: int) -> List[dict]:
            changes = []
            if row == 0:
                return changes
            base_valid = deltas.base_valid
            p_idx, valid, metric, lanes = deltas.deltas_of_row(row)
            for k in range(len(p_idx)):
                p = int(p_idx[k])
                prefix = self._prefixes[p]
                if prefix_is_v4(prefix) and not v4_ok:
                    continue
                was, now = bool(base_valid[p]), bool(valid[k])
                changes.append(
                    {
                        "prefix": prefix,
                        "change": change_kind(was, now),
                        "old_nexthops": (
                            lanes_to_names(deltas.base_lanes[p])
                            if was
                            else []
                        ),
                        "new_nexthops": (
                            lanes_to_names(lanes[k]) if now else []
                        ),
                        "old_metric": (
                            float(deltas.base_metric[p]) if was else None
                        ),
                        "new_metric": float(metric[k]) if now else None,
                    }
                )
            return changes

        if simultaneous:
            bad = [e for e in errors if e is not None]
            if bad:
                return {
                    "eligible": True,
                    "vantage": me,
                    "engine": "device",
                    "simultaneous": True,
                    "failures": bad,
                }
            fail_set = tuple(
                int(l) for tup in lid_sets for l in tup  # type: ignore[union-attr]
            )
            deltas = self._selector.run(
                self._sweep.run_sets([fail_set], fetch=False)
            )
            self.num_sweeps += 1
            changes = changes_from_row(deltas, int(deltas.snap_row[0]))
            on_dag = self._sweep.on_dag_links()
            return {
                "eligible": True,
                "vantage": me,
                "engine": "device",
                "simultaneous": True,
                "failures": [
                    {
                        "links": [list(f) for f in link_failures],
                        "on_shortest_path_dag": bool(
                            any(on_dag[l] for l in fail_set)
                        ),
                        "routes_changed": len(changes),
                        "changes": changes,
                    }
                ],
            }

        # per-failure snapshots: a parallel bundle is one snapshot that
        # fails its whole link set; error rows become empty sets (base)
        deltas = self._selector.run(
            self._sweep.run_sets(
                [s if s is not None else () for s in lid_sets],
                fetch=False,
            )
        )
        self.num_sweeps += 1

        on_dag = self._sweep.on_dag_links()
        out = []
        for s, ((n1, n2), tup) in enumerate(zip(link_failures, lid_sets)):
            if tup is None:
                out.append(errors[s])
                continue
            changes = changes_from_row(deltas, int(deltas.snap_row[s]))
            entry = {
                "link": [n1, n2],
                "on_shortest_path_dag": bool(
                    any(on_dag[l] for l in tup)
                ),
                "routes_changed": len(changes),
                "changes": changes,
            }
            if len(tup) > 1:
                # the pair is a bundle (parallel links): ALL failed
                entry["links_failed"] = len(tup)
            out.append(entry)
        return {"eligible": True, "vantage": me, "engine": "device", "failures": out}


def _whatif_engine_criticality(
    engine: "WhatIfApiEngine",
    area_link_states,
    prefix_state,
    change_seq: int,
    max_pairs: int = 0,
) -> Dict:
    """Criticality report over the engine's cached sweep context."""
    engine._engine_for(area_link_states, prefix_state, change_seq)
    v4_ok = engine.solver.enable_v4 or engine.solver.v4_over_v6_nexthop
    return _criticality_from_engine(
        engine._sweep,
        engine._selector,
        engine._topo,
        engine._prefixes,
        max_pairs,
        v4_ok,
    )


def _criticality_from_engine(
    sweep, selector, topo, prefixes, max_pairs: int, v4_ok: bool
) -> Dict:
    """Shared criticality computation over a (sweep, selector) pair:
    one single-failure sweep across EVERY link ranks blast radius; an
    optional double-failure run_sets scan (capped at ``max_pairs``)
    finds pairs whose combined failure withdraws routes that neither
    single failure withdraws (partition risk).  Pairs with at least
    one on-DAG member are scanned — an off-DAG link can carry the
    reroute once its on-DAG partner fails (the canonical
    primary+backup partition case), but a pair of two off-DAG links
    provably changes nothing.  Counts skip v4 prefixes the node would
    never install (same filter the what-if answers apply)."""
    import itertools

    L = len(topo.links)
    fails = np.arange(L, dtype=np.int32)
    deltas = selector.run(sweep.run(fails, fetch=False))
    on_dag = sweep.on_dag_links()
    #: prefix rows excluded from counts (v4 on a v6-only node)
    skip_p = (
        np.asarray([prefix_is_v4(p) for p in prefixes], bool)
        if not v4_ok
        else np.zeros(len(prefixes), bool)
    )

    def removed_of_row(dl, row: int):
        if row == 0:
            return 0, 0
        p_idx, valid, _m, _l = dl.deltas_of_row(row)
        keep = ~skip_p[p_idx]
        removed = int((~valid[keep]).sum())
        return int(keep.sum()), removed

    links = []
    single_removed = {}
    for li in range(L):
        changed, removed = removed_of_row(deltas, int(deltas.snap_row[li]))
        link = topo.links[li]
        single_removed[li] = removed
        links.append(
            {
                "link": sorted((link.n1, link.n2)),
                "on_shortest_path_dag": bool(on_dag[li]),
                "routes_changed": changed,
                "routes_withdrawn": removed,
            }
        )
    links.sort(
        key=lambda e: (-e["routes_withdrawn"], -e["routes_changed"],
                       e["link"])
    )

    pairs_out = None
    if max_pairs > 0:
        n_off = int((~on_dag[:L]).sum())
        # pairs with >= 1 on-DAG member, capped WITHOUT materializing
        # the full O(L^2) product
        def gen_pairs():
            for a, b in itertools.combinations(range(L), 2):
                if on_dag[a] or on_dag[b]:
                    yield (a, b)

        capped = list(itertools.islice(gen_pairs(), max_pairs))
        total = L * (L - 1) // 2 - n_off * (n_off - 1) // 2
        pair_deltas = selector.run(
            sweep.run_sets(capped, fetch=False)
        )
        risky = []
        for s, (a, b) in enumerate(capped):
            _c, removed = removed_of_row(
                pair_deltas, int(pair_deltas.snap_row[s])
            )
            extra = removed - single_removed[a] - single_removed[b]
            if extra > 0:
                la, lb = topo.links[a], topo.links[b]
                risky.append(
                    {
                        "links": [
                            sorted((la.n1, la.n2)),
                            sorted((lb.n1, lb.n2)),
                        ],
                        "routes_withdrawn": removed,
                        "beyond_single_failures": extra,
                    }
                )
        risky.sort(key=lambda e: -e["beyond_single_failures"])
        pairs_out = {
            "checked": len(capped),
            "total": total,
            "truncated": len(capped) < total,
            "risky": risky[:64],
            "risky_count": len(risky),
            "risky_truncated": len(risky) > 64,
        }
    return {"links": links, "pairs": pairs_out}


class MultiAreaWhatIfEngine:
    """Multi-area link-failure what-if from this node's vantage.

    Tables (topology encode, candidate table, base snapshot) are cached
    per LSDB change generation; each ``run`` solves the candidate
    failures plus one base snapshot as a single device batch and decodes
    only the prefixes whose merged route view changed."""

    def __init__(
        self, solver: SpfSolver, mesh=None, pool=None, probe=None
    ) -> None:
        """``mesh``: optional ``jax.sharding.Mesh`` with a ``batch``
        axis — failure snapshots then shard across the mesh
        (ops.fleet_tables.sharded_whatif_tables), bit-identical to the
        unsharded kernel.  ``pool``: optional
        :class:`~openr_tpu.parallel.mesh.DevicePool` — the failure
        batch then splits contiguously over the pool's HEALTHY chips as
        committed per-device dispatches (no shard_map requirement; a
        quarantined chip's share re-packs onto the survivors).
        ``probe``: optional
        :class:`~openr_tpu.tracing.pipeline.PipelineProbe` sharing the
        backend's phase/busy ledger."""
        from openr_tpu.tracing.pipeline import disabled_probe

        self.solver = solver
        self.mesh = mesh
        self.pool = pool
        self.probe = probe if probe is not None else disabled_probe()
        self._cache_key = None
        self._state = None
        #: PR-6 remnant: with BOTH a mesh and a pool, the collective
        #: mesh re-derives from DevicePool.survivor_mesh() on every
        #: health transition, so the shard_map path re-packs on chip
        #: quarantine exactly like the committed-dispatch path
        self._mesh_health_seq = None
        self._mesh_requested = mesh is not None
        self.num_engine_builds = 0
        self.num_sweeps = 0
        self.num_pool_dispatches = 0

    def _active_mesh(self):
        if not self._mesh_requested:
            return None
        if self.pool is None:
            return self.mesh
        if self._mesh_health_seq != self.pool.health_seq:
            self.mesh = self.pool.survivor_mesh()
            self._mesh_health_seq = self.pool.health_seq
        return self.mesh

    def _context(self, area_link_states, prefix_state, change_seq):
        import numpy as np

        from openr_tpu.decision.backend import DEGREE_BUCKETS
        from openr_tpu.decision.cand_table import CandidateTable
        from openr_tpu.ops.csr import bucket_for, encode_multi_area

        key = (
            tuple(
                (a, area_link_states[a].topology_seq)
                for a in sorted(area_link_states)
            ),
            change_seq,
        )
        if self._cache_key == key and self._state is not None:
            return self._state
        from openr_tpu.tracing import pipeline

        me = self.solver.my_node_name
        with self.probe.phase(pipeline.ENCODE):
            enc = encode_multi_area(area_link_states, me)
        with self.probe.phase(pipeline.HOST_FETCH):
            table = CandidateTable()
            table.full_sync(prefix_state)
            dv = table.derived(enc)
            link_index = np.stack([t.link_index for t in enc.topos])
            # (n1, n2) -> [(area_index, link_id)]; parallel links (within
            # or across areas) are rejected like the single-area engine
            pair_links: Dict[frozenset, list] = {}
            for ai, t in enumerate(enc.topos):
                for pair, vals in build_pair_links(
                    t.links, area_index=ai
                ).items():
                    pair_links.setdefault(pair, []).extend(vals)
            out_edges_by_area = [t.root_out_edges(me) for t in enc.topos]
        D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
        self._state = dict(
            enc=enc,
            table=table,
            dv=dv,
            link_index=link_index,
            pair_links=pair_links,
            out_edges_by_area=out_edges_by_area,
            D=D,
            base_dist=None,  # filled on first run (on-DAG flags)
        )
        self._cache_key = key
        self.num_engine_builds += 1
        return self._state

    def run(
        self,
        link_failures: List[Tuple[str, str]],
        area_link_states,
        prefix_state,
        change_seq: int,
        simultaneous: bool = False,
    ) -> Dict:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from openr_tpu.ops.fleet_tables import whatif_multi_area_tables
        from openr_tpu.ops.route_select import multi_area_spf_tables
        from openr_tpu.types import RouteComputationRules

        st = self._context(area_link_states, prefix_state, change_seq)
        enc, dv, table = st["enc"], st["dv"], st["table"]
        me = self.solver.my_node_name
        A = enc.num_areas
        per_area = (
            self.solver.route_selection_algorithm
            == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
        )

        # resolve candidate failures (shared semantics with the
        # single-area engine); every value is a TUPLE of (area, link)
        # hits — parallel bundles and simultaneous sets fail together
        # (the kernel masks up to S links per snapshot)
        pairs, errors = resolve_pair_failures(
            st["pair_links"], link_failures, allow_parallel=True
        )
        if simultaneous:
            bad = [e for e in errors if e is not None]
            if bad:
                return {
                    "eligible": True,
                    "vantage": me,
                    "engine": "multiarea",
                    "simultaneous": True,
                    "failures": bad,
                }
            # ONE snapshot failing the union of every listed link
            union = tuple(
                hit for tup in pairs if tup is not None for hit in tup
            )
            fail_sets: List[Optional[tuple]] = [union]
        else:
            fail_sets = pairs
        B = len(fail_sets)
        from openr_tpu.ops.csr import bucket_for

        # pad the batch to a bucket STRICTLY larger than B so jit shapes
        # stay cache-stable across query sizes AND at least one -1 pad
        # row exists — that row solves the unperturbed topology and
        # doubles as the base snapshot (an explicit base row would cost
        # the same as the padding the bucket already requires).  The set
        # width S is bucketed too (most queries are single links: S=1).
        bucket = bucket_for(
            B + 1, FAILURE_BUCKETS + (max(B + 1, FAILURE_BUCKETS[-1]),)
        )
        mesh = self._active_mesh()
        if mesh is not None:
            # sharded dispatch splits the failure batch across devices
            gran = mesh.devices.size
            bucket = ((bucket + gran - 1) // gran) * gran
        from openr_tpu.tracing import pipeline

        smax = max(
            [len(tup) for tup in fail_sets if tup is not None] or [1]
        )
        with self.probe.phase(pipeline.PAD_PACK):
            S = bucket_for(smax, (1, 2, 4, 8, 16, 32, max(smax, 32)))
            fa = np.full((bucket, S), -1, np.int32)
            fl = np.full((bucket, S), -1, np.int32)
            for i, tup in enumerate(fail_sets):
                if tup is not None:
                    for s, (ai, li) in enumerate(tup):
                        fa[i, s], fl[i, s] = ai, li

        from openr_tpu.ops.jit_guard import call_jit_guarded

        with self.probe.phase(pipeline.TRANSFER):
            kernel_args = dict(
                src=jnp.asarray(enc.src),
                dst=jnp.asarray(enc.dst),
                w=jnp.asarray(enc.w),
                edge_ok=jnp.asarray(enc.edge_ok),
                link_index=jnp.asarray(st["link_index"]),
                overloaded=jnp.asarray(enc.overloaded),
                soft=jnp.asarray(enc.soft),
                roots=jnp.asarray(enc.roots),
            )
            cand_args = dict(
                cand_area=jnp.asarray(dv.cand_area),
                cand_node=jnp.asarray(dv.cand_node),
                cand_ok=jnp.asarray(dv.cand_ok),
                drain_metric=jnp.asarray(dv.drain_metric),
                path_pref=jnp.asarray(dv.path_pref),
                source_pref=jnp.asarray(dv.source_pref),
                distance=jnp.asarray(dv.distance),
                cand_node_in_area=jnp.asarray(dv.cand_node_in_area),
            )
        if mesh is not None:
            from openr_tpu.ops.fleet_tables import sharded_whatif_tables
            from openr_tpu.parallel.mesh import batch_sharding, replicated

            rep = replicated(mesh)
            bat = batch_sharding(mesh)
            fn = sharded_whatif_tables(mesh, st["D"], per_area)
            use, shortest, lanes, valid = jax.device_get(
                call_jit_guarded(
                    fn,
                    *(
                        jax.device_put(v, rep)
                        for v in kernel_args.values()
                    ),
                    jax.device_put(jnp.asarray(fa), bat),
                    jax.device_put(jnp.asarray(fl), bat),
                    *(
                        jax.device_put(v, rep)
                        for v in cand_args.values()
                    ),
                )
            )
        else:
            pool_devs = None
            if self.pool is not None and B >= 2:
                healthy = self.pool.healthy_indices()
                if len(healthy) > 1:
                    pool_devs = healthy
            if pool_devs is not None:
                # data-parallel over the pool: contiguous failure-row
                # shards, one committed dispatch per healthy chip, each
                # with its own -1 pad row (the pad row solves the
                # unperturbed topology, so every shard carries a base —
                # the first shard's is the one the decode diffs against).
                # Shards drain as STREAMED completions (is_ready poll +
                # per-shard stream_drain charged only to the completing
                # chip) instead of one all-chip device_get barrier.
                from openr_tpu.ops import jit_guard

                shards = self.pool.shard_ranges(B, pool_devs)
                dispatched = []
                for idx, lo, hi in shards:
                    n_i = hi - lo
                    with self.probe.phase(pipeline.PAD_PACK, device=idx):
                        bucket_i = bucket_for(
                            n_i + 1,
                            FAILURE_BUCKETS
                            + (max(n_i + 1, FAILURE_BUCKETS[-1]),),
                        )
                        fa_i = np.full((bucket_i, S), -1, np.int32)
                        fl_i = np.full((bucket_i, S), -1, np.int32)
                        fa_i[:n_i] = fa[lo:hi]
                        fl_i[:n_i] = fl[lo:hi]
                    d = self.pool.device(idx)
                    with self.probe.phase(pipeline.TRANSFER, device=idx):
                        shard_kwargs = dict(
                            fail_area=jax.device_put(jnp.asarray(fa_i), d),
                            fail_link=jax.device_put(jnp.asarray(fl_i), d),
                            **{
                                k: jax.device_put(v, d)
                                for k, v in kernel_args.items()
                            },
                            **{
                                k: jax.device_put(v, d)
                                for k, v in cand_args.items()
                            },
                        )
                    with self.probe.phase(
                        pipeline.DEVICE_COMPUTE, device=idx
                    ), jit_guard.dispatch_device(idx):
                        out = call_jit_guarded(
                            whatif_multi_area_tables,
                            max_degree=st["D"],
                            per_area_distance=per_area,
                            **shard_kwargs,
                        )
                    self.pool.note_inflight(idx)
                    for o in out:
                        o.copy_to_host_async()
                    dispatched.append((idx, n_i, out))
                    self.num_pool_dispatches += 1
                fetched_by_pos: Dict[int, tuple] = {}
                pending_shards = list(enumerate(dispatched))
                while pending_shards:
                    sel = 0
                    for j, (_p, r) in enumerate(pending_shards):
                        if all(o.is_ready() for o in r[2]):
                            sel = j
                            break
                    pos, rec = pending_shards.pop(sel)
                    idx, _n_i, out = rec
                    with self.probe.phase(
                        pipeline.STREAM_DRAIN, device=idx
                    ):
                        for o in out:
                            o.block_until_ready()
                    self.pool.note_complete(idx)
                    with self.probe.phase(
                        pipeline.DEVICE_GET, device=idx
                    ):
                        fetched_by_pos[pos] = jax.device_get(out)
                fetched = [
                    fetched_by_pos[i] for i in range(len(dispatched))
                ]
                parts = []
                for k in range(4):
                    rows = [
                        outs[k][:n]
                        for (_i, n, _), outs in zip(dispatched, fetched)
                    ]
                    # base snapshot: the FIRST shard's pad row, placed
                    # at index B exactly where the unsharded layout
                    # puts it (all shards' pad rows are bit-identical —
                    # same kernel, same unperturbed inputs)
                    n0 = dispatched[0][1]
                    rows.append(fetched[0][k][n0 : n0 + 1])
                    parts.append(np.concatenate(rows, axis=0))
                use, shortest, lanes, valid = parts
            else:
                with self.probe.phase(pipeline.DEVICE_COMPUTE, device=0):
                    pending = call_jit_guarded(
                        whatif_multi_area_tables,
                        fail_area=jnp.asarray(fa),
                        fail_link=jnp.asarray(fl),
                        max_degree=st["D"],
                        per_area_distance=per_area,
                        **kernel_args,
                        **cand_args,
                    )
                with self.probe.phase(pipeline.DEVICE_GET, device=0):
                    use, shortest, lanes, valid = jax.device_get(pending)
        if st["base_dist"] is None:
            with self.probe.phase(pipeline.DEVICE_COMPUTE):
                dist, _nh = call_jit_guarded(
                    multi_area_spf_tables,
                    kernel_args["src"],
                    kernel_args["dst"],
                    kernel_args["w"],
                    kernel_args["edge_ok"],
                    kernel_args["overloaded"],
                    kernel_args["roots"],
                    max_degree=st["D"],
                )
            with self.probe.phase(pipeline.DEVICE_GET):
                st["base_dist"] = np.asarray(jax.device_get(dist))
        self.num_sweeps += 1

        # ---- merged route view per snapshot (SpfSolver.cpp:276-302) ----
        with self.probe.phase(pipeline.DECODE):
            B1, P, _A = valid.shape
            m = np.where(valid, shortest, np.inf)  # [B1, P, A]
            m_star = m.min(axis=2)  # [B1, P]
            at_min = valid & (m == m_star[:, :, None])
            eff_lanes = lanes & at_min[:, :, :, None]  # [B1, P, A, D]
            merged = eff_lanes.sum(axis=(2, 3))  # nexthop count
            req = np.max(
                np.where(use, dv.min_nexthop[None, :, :], 0), axis=2
            )  # [B1, P]
            my_gid = table._node_gid.get(me)
            if my_gid is None:
                self_win = np.zeros((B1, P), bool)
            else:
                self_win = (
                    use & (table.adv_gid[None, :, :] == my_gid)
                ).any(axis=2)
            v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop
            include = np.asarray(
                [
                    p is not None and (v4_ok or not prefix_is_v4(p))
                    for p in table.row_prefix
                ],
                bool,
            )
            route_ok = (
                include[None, :]
                & valid.any(axis=2)
                & ~self_win
                & (merged > 0)
                & (merged >= req)
            )

        base = B  # the first pad row: the unperturbed snapshot
        out_edges_by_area = st["out_edges_by_area"]

        def nh_names(b, p):
            names = []
            for ai, lane in zip(*np.nonzero(eff_lanes[b, p])):
                oe = out_edges_by_area[ai]
                if lane < len(oe):
                    names.append(oe[lane][1])
            return sorted(set(names))

        # on-DAG flag per (area, link): some directed edge of the link
        # lies on a shortest path from me in its area
        bd = st["base_dist"]

        def on_dag(ai, li):
            t = enc.topos[ai]
            es = np.nonzero(t.link_index == li)[0]
            d = bd[ai]
            transit = (~t.overloaded) | (
                np.arange(t.padded_nodes) == int(enc.roots[ai])
            )
            for e in es:
                u, v = int(t.src[e]), int(t.dst[e])
                if (
                    t.edge_ok[e]
                    and transit[u]
                    and d[u] < 3.0e38
                    and d[v] < 3.0e38
                    and d[u] + t.w[e] == d[v]
                ):
                    return True
            return False

        def changes_for(s) -> List[dict]:
            # changed prefixes: validity flipped, metric moved, or the
            # merged ECMP lane set moved
            diff = (route_ok[s] != route_ok[base]) | (
                route_ok[s]
                & route_ok[base]
                & (
                    (m_star[s] != m_star[base])
                    | (eff_lanes[s] != eff_lanes[base]).any(axis=(1, 2))
                )
            )
            changes = []
            for p in np.nonzero(diff)[0]:
                was, now = bool(route_ok[base, p]), bool(route_ok[s, p])
                changes.append(
                    {
                        "prefix": table.row_prefix[p],
                        "change": change_kind(was, now),
                        "old_nexthops": nh_names(base, p) if was else [],
                        "new_nexthops": nh_names(s, p) if now else [],
                        "old_metric": (
                            float(m_star[base, p]) if was else None
                        ),
                        "new_metric": float(m_star[s, p]) if now else None,
                    }
                )
            return changes

        if simultaneous:
            with self.probe.phase(pipeline.DECODE):
                changes = changes_for(0)
                any_on_dag = bool(
                    any(on_dag(ai, li) for ai, li in (fail_sets[0] or ()))
                )
            return {
                "eligible": True,
                "vantage": me,
                "engine": "multiarea",
                "simultaneous": True,
                "failures": [
                    {
                        "links": [list(f) for f in link_failures],
                        "on_shortest_path_dag": any_on_dag,
                        "routes_changed": len(changes),
                        "changes": changes,
                    }
                ],
            }

        out = []
        with self.probe.phase(pipeline.DECODE):
            for s, ((n1, n2), tup) in enumerate(zip(link_failures, pairs)):
                if tup is None:
                    out.append(errors[s])
                    continue
                changes = changes_for(s)
                entry = {
                    "link": [n1, n2],
                    "area": enc.areas[tup[0][0]],
                    "on_shortest_path_dag": bool(
                        any(on_dag(ai, li) for ai, li in tup)
                    ),
                    "routes_changed": len(changes),
                    "changes": changes,
                }
                if len(tup) > 1:
                    # parallel bundle (within or across areas): every
                    # member failed at once as one set
                    entry["links_failed"] = len(tup)
                    entry["areas"] = sorted(
                        {enc.areas[ai] for ai, _ in tup}
                    )
                out.append(entry)
        return {
            "eligible": True,
            "vantage": me,
            "engine": "multiarea",
            "failures": out,
        }


class NativeWhatIfEngine:
    """Single-area what-if over the NATIVE warm-start sweep.

    The C++ incremental-repair solver (native/spf_scalar.cc
    spf_warm_sweep — the same off-DAG-skip + affected-region trick the
    device kernel uses) solves a single-link failure in tens of
    microseconds at 1024-node scale; over a TUNNELED device the what-if
    device path pays 1-2 dispatch round trips (~75 ms each) before any
    compute.  For small operator queries the native engine is therefore
    the right backend, and Decision auto-picks it from the measured
    dispatch round trip (the same calibration the Decision backend's
    device cutover uses).  Output schema and selection semantics are
    identical to WhatIfApiEngine — selection runs the numpy mirror of
    the device chain (ops.np_select.select_routes_numpy, jax-free so
    scalar-only deployments never load the device stack), so the two
    engines are interchangeable and parity-tested.
    """

    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver
        self._cache_key = None
        self._ctx = None
        self.num_engine_builds = 0
        self.num_sweeps = 0

    def _engine_for(self, area_link_states, prefix_state, change_seq):
        from openr_tpu.ops.csr import (
            encode_link_state,
            encode_prefix_candidates,
        )
        from openr_tpu.ops.native_spf import NativeSpf
        from openr_tpu.ops.np_select import select_routes_numpy

        (area, ls), = area_link_states.items()
        key = (area, ls.topology_seq, change_seq)
        if self._cache_key == key:
            return self._ctx
        topo = encode_link_state(ls)
        me = self.solver.my_node_name
        cands = encode_prefix_candidates(prefix_state, topo, area)
        native = NativeSpf(topo, me)
        native.warm_prepare()
        # shared lane-count formula (ops.whatif.root_lane_count) — a
        # third independent implementation here could silently diverge
        # from the device engine and the bench on padded topologies
        from openr_tpu.ops.whatif import root_lane_count

        D = root_lane_count(topo, topo.node_id(me))
        soft = np.zeros(topo.padded_nodes, np.int32)
        sel_args = (
            cands.cand_node,
            cands.cand_ok,
            cands.drain_metric,
            cands.path_pref,
            cands.source_pref,
            cands.distance,
            cands.min_nexthop,
        )
        base_dist, base_nh_mask = native.warm_base
        base_lanes = native.lanes_dense(D, mask=base_nh_mask)
        bvalid, bmetric, bnh, _n, _u = select_routes_numpy(
            *sel_args,
            base_dist,
            base_lanes,
            topo.overloaded,
            soft,
            topo.node_id(me),
        )
        pair_links = build_pair_links(topo.links)
        self._ctx = dict(
            topo=topo,
            native=native,
            cands=cands,
            D=D,
            soft=soft,
            sel_args=sel_args,
            base=(bvalid, bmetric, bnh),
            pair_links=pair_links,
            lane_names=lane_names_for(topo, me),
            root_id=topo.node_id(me),
        )
        self._cache_key = key
        self.num_engine_builds += 1
        return self._ctx

    def run(
        self,
        link_failures: List[Tuple[str, str]],
        area_link_states,
        prefix_state,
        change_seq: int,
        simultaneous: bool = False,
    ) -> Dict:
        from openr_tpu.ops.np_select import select_routes_numpy

        ctx = self._engine_for(area_link_states, prefix_state, change_seq)
        me = self.solver.my_node_name
        topo, native, D = ctx["topo"], ctx["native"], ctx["D"]
        bvalid, bmetric, bnh = ctx["base"]
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop
        prefixes = ctx["cands"].prefixes
        lane_names = ctx["lane_names"]

        def lanes_to_names(row) -> List[str]:
            return decode_lane_names(lane_names, row)

        lid_sets, errors = resolve_pair_failures(
            ctx["pair_links"], link_failures, allow_parallel=True
        )
        self.num_sweeps += 1

        def select_current():
            lanes = native.lanes_dense(D)
            return select_routes_numpy(
                *ctx["sel_args"],
                native.dist,
                lanes,
                topo.overloaded,
                ctx["soft"],
                ctx["root_id"],
            )

        def diff_changes(valid, metric, nh_out) -> List[dict]:
            diff = (valid != bvalid) | (
                valid
                & bvalid
                & ((metric != bmetric) | (nh_out != bnh).any(axis=1))
            )
            changes = []
            for p in np.nonzero(diff)[0]:
                prefix = prefixes[p]
                if prefix_is_v4(prefix) and not v4_ok:
                    continue
                was, now = bool(bvalid[p]), bool(valid[p])
                changes.append(
                    {
                        "prefix": prefix,
                        "change": change_kind(was, now),
                        "old_nexthops": (
                            lanes_to_names(bnh[p]) if was else []
                        ),
                        "new_nexthops": (
                            lanes_to_names(nh_out[p]) if now else []
                        ),
                        "old_metric": float(bmetric[p]) if was else None,
                        "new_metric": float(metric[p]) if now else None,
                    }
                )
            return changes

        if simultaneous:
            bad = [e for e in errors if e is not None]
            if bad:
                return {
                    "eligible": True,
                    "vantage": me,
                    "engine": "native",
                    "simultaneous": True,
                    "failures": bad,
                }
            all_lids = [l for tup in lid_sets for l in tup]  # type: ignore[union-attr]
            any_on_dag = any(native.link_on_dag[l] for l in all_lids)
            if any_on_dag:
                # native multi-link cold solve with the FULL set — an
                # off-DAG member can carry the reroute once on-DAG
                # members fail, so it must be removed too.  Only a set
                # with NO on-DAG member provably changes nothing.
                native.solve_set(all_lids)
                valid, metric, nh_out, _n, _u = select_current()
                changes = diff_changes(valid, metric, nh_out)
            else:
                changes = []
            return {
                "eligible": True,
                "vantage": me,
                "engine": "native",
                "simultaneous": True,
                "failures": [
                    {
                        "links": [list(f) for f in link_failures],
                        "on_shortest_path_dag": any_on_dag,
                        "routes_changed": len(changes),
                        "changes": changes,
                    }
                ],
            }

        out = []
        for s, ((n1, n2), tup) in enumerate(zip(link_failures, lid_sets)):
            if tup is None:
                out.append(errors[s])
                continue
            on_dag = bool(any(native.link_on_dag[l] for l in tup))
            changes = []
            if on_dag:
                if len(tup) == 1:
                    # single link: the warm incremental sweep
                    native.warm_sweep(
                        np.asarray([tup[0]], np.int32), keep_last=True
                    )
                else:
                    # parallel bundle: fail every member at once (cold
                    # set solve — same removal the device engine does)
                    native.solve_set(list(tup))
                valid, metric, nh_out, _n, _u = select_current()
                changes = diff_changes(valid, metric, nh_out)
            entry = {
                "link": [n1, n2],
                "on_shortest_path_dag": on_dag,
                "routes_changed": len(changes),
                "changes": changes,
            }
            if len(tup) > 1:
                entry["links_failed"] = len(tup)
            out.append(entry)
        return {"eligible": True, "vantage": me, "engine": "native", "failures": out}


class GenericSolverWhatIfEngine:
    """Algorithm-complete what-if fallback: rebuild the LSDB with the
    candidate links actually removed and run the FULL SpfSolver (the
    same selection code every installed route went through), then diff
    the route databases.

    This is the slow path — one full scalar build per failure (or one
    for a simultaneous set) — but it supports everything
    ``build_route_db`` supports: KSP2_ED_ECMP, any
    route_selection_algorithm, multi-area LSDBs, cross-area
    redistribution, simultaneous sets.  jax-free, so scalar-only
    deployments use it without loading the device stack.  It serves the
    queries the fast engines decline (reference
    Decision.cpp:342 getDecisionRouteDb computes any configured
    algorithm; our fast engines cover the SHORTEST_DISTANCE family).
    """

    engine_label = "generic-solver"

    def __init__(self, solver) -> None:
        self.solver = solver
        self.num_builds = 0
        self._cache_key = None
        self._base_view = None
        self._pair_links: Dict = {}

    def _build(self, states, prefix_state):
        """One full route build; subclasses swap the compute engine."""
        return self.solver.build_route_db(states, prefix_state)

    @staticmethod
    def _pairs_map(area_link_states) -> Dict:
        """pair -> occurrences across every area, through the SHARED
        build_pair_links so link-identity semantics live in one place
        (only uniqueness of the pair is consumed)."""
        m: Dict = {}
        for _area, ls in sorted(area_link_states.items()):
            for pair, vals in build_pair_links(ls.all_links()).items():
                m.setdefault(pair, []).extend(vals)
        return m

    @staticmethod
    def _states_without(area_link_states, drop_pairs) -> Dict:
        import dataclasses

        from openr_tpu.decision.link_state import LinkState

        out: Dict = {}
        for area, ls in area_link_states.items():
            nls = LinkState(area, ls.my_node_name)
            for _node, db in sorted(ls.get_adjacency_databases().items()):
                filtered = dataclasses.replace(
                    db,
                    adjacencies=[
                        a
                        for a in db.adjacencies
                        if frozenset(
                            (db.this_node_name, a.other_node_name)
                        )
                        not in drop_pairs
                    ],
                )
                nls.update_adjacency_database(filtered)
            out[area] = nls
        return out

    def run(
        self,
        link_failures: List[Tuple[str, str]],
        area_link_states,
        prefix_state,
        change_seq: int,
        simultaneous: bool = False,
    ) -> Optional[Dict]:
        me = self.solver.my_node_name

        def view(db):
            if db is None:  # vantage absent from the (modified) LSDB
                return {}
            return {
                p: (
                    float(e.igp_cost),
                    sorted({n.neighbor_node_name for n in e.nexthops}),
                )
                for p, e in db.unicast_routes.items()
            }

        # base view + pair map cached per LSDB generation, like every
        # other what-if engine
        key = (
            change_seq,
            tuple(
                (a, area_link_states[a].topology_seq)
                for a in sorted(area_link_states)
            ),
        )
        if self._cache_key != key:
            base = self._build(area_link_states, prefix_state)
            self.num_builds += 1
            if base is None:
                return None  # no vantage in the LSDB yet -> ineligible
            self._base_view = view(base)
            self._pair_links = self._pairs_map(area_link_states)
            self._cache_key = key
        base_view = self._base_view
        # parallel bundles are fine here: removal is by node PAIR, which
        # drops every parallel adjacency at once
        resolved, errors = resolve_pair_failures(
            self._pair_links, link_failures, allow_parallel=True
        )
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop

        def diff_against(mod_db) -> List[dict]:
            mod_view = view(mod_db)
            changes = []
            for p in sorted(set(base_view) | set(mod_view)):
                if prefix_is_v4(p) and not v4_ok:
                    continue
                old, new = base_view.get(p), mod_view.get(p)
                if old == new:
                    continue
                changes.append(
                    {
                        "prefix": p,
                        "change": change_kind(
                            old is not None, new is not None
                        ),
                        "old_nexthops": old[1] if old else [],
                        "new_nexthops": new[1] if new else [],
                        "old_metric": old[0] if old else None,
                        "new_metric": new[0] if new else None,
                    }
                )
            return changes

        def solve_without(drop_pairs) -> List[dict]:
            mod = self._states_without(area_link_states, drop_pairs)
            self.num_builds += 1
            return diff_against(self._build(mod, prefix_state))

        if simultaneous:
            bad = [e for e in errors if e is not None]
            if bad:
                return {
                    "eligible": True,
                    "vantage": me,
                    "engine": self.engine_label,
                    "simultaneous": True,
                    "failures": bad,
                }
            changes = solve_without(
                {frozenset(p) for p in link_failures}
            )
            return {
                "eligible": True,
                "vantage": me,
                "engine": self.engine_label,
                "simultaneous": True,
                "failures": [
                    {
                        "links": [list(f) for f in link_failures],
                        "on_shortest_path_dag": bool(changes),
                        "routes_changed": len(changes),
                        "changes": changes,
                    }
                ],
            }

        out = []
        for (n1, n2), hit, err in zip(link_failures, resolved, errors):
            if hit is None:
                out.append(err)
                continue
            changes = solve_without({frozenset((n1, n2))})
            entry = {
                "link": [n1, n2],
                "on_shortest_path_dag": bool(changes),
                "routes_changed": len(changes),
                "changes": changes,
            }
            if len(hit) > 1:
                # bundle: parallel links in one area, or the pair's
                # links across several areas — all removed at once
                entry["links_failed"] = len(hit)
            out.append(entry)
        return {
            "eligible": True,
            "vantage": me,
            "engine": self.engine_label,
            "failures": out,
        }


class DeviceBuildWhatIfEngine(GenericSolverWhatIfEngine):
    """What-if for configurations OUTSIDE the sweep kernels' algebra —
    KSP2_ED_ECMP prefixes in the LSDB, exotic selection rules — served
    by DEVICE full builds instead of the scalar solver.

    Same structure as the generic fallback (rebuild the LSDB minus the
    candidate links, diff), but each build runs through a dedicated
    TpuBackend: SPF + selection tables on device and KSP2 prefixes on
    the device KSP2 engine (decision/ksp2.py) — the identical compute
    path the daemon's own route builds use for these algorithms, so
    parity with installed routes is by construction.  O(failures)
    device builds rather than the O(1) sweep, but every build after the
    first reuses warm jit shapes; at reference scale that is orders of
    magnitude faster than the per-failure scalar build (the reference
    solves any-algorithm what-ifs scalar via getDecisionRouteDb,
    Decision.cpp:342 — this is that surface, accelerated).

    Builds that the backend itself declines (unsupported selection
    algorithm) transparently run scalar inside TpuBackend — answers
    never differ from GenericSolverWhatIfEngine, only their speed.
    """

    engine_label = "device-build"

    def __init__(self, solver) -> None:
        super().__init__(solver)
        from openr_tpu.decision.backend import TpuBackend

        #: dedicated backend: what-if builds on modified topologies must
        #: never pollute the daemon backend's encoding/table caches.
        #: min_device_prefixes=0 pins always-device (deterministic)
        #: explicitly rather than relying on the constructor default.
        self._backend = TpuBackend(solver, min_device_prefixes=0)

    def _build(self, states, prefix_state):
        return self._backend.build_route_db(
            states, prefix_state, force_full=True, cache_result=False
        )
