"""Operator-facing what-if: 'which of MY routes change if link X fails?'

Wires the flagship sweep engine (ops/whatif.py + ops/sweep_select.py)
into the daemon: the ctrl call takes a list of candidate link failures,
runs them as one device batch against the CURRENT LSDB from this node's
vantage, and returns per-failure route deltas (removed / rerouted /
metric-changed) decoded to neighbor names.  The engine (base solve +
repair plan + selection tables) is cached per LSDB change generation,
so an operator sweeping many links pays the setup once.

Single-area SHORTEST_DISTANCE vantage (the fleet-engine eligibility);
anything else returns eligible=False and the operator falls back to
per-failure scalar what-ifs via getRouteDbComputed semantics.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.types import prefix_is_v4


class WhatIfApiEngine:
    """Cached sweep→routes pipeline for one node's vantage."""

    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver
        self._cache_key = None
        self._sweep = None
        self._selector = None
        self._topo = None
        self._prefixes: List[str] = []
        self.num_engine_builds = 0
        self.num_sweeps = 0

    def _engine_for(self, area_link_states, prefix_state, change_seq):
        from openr_tpu.ops.csr import encode_link_state, encode_prefix_candidates
        from openr_tpu.ops.sweep_select import SweepRouteSelector
        from openr_tpu.ops.whatif import LinkFailureSweep

        (area, ls), = area_link_states.items()
        key = (area, ls.topology_seq, change_seq)
        if self._cache_key == key:
            return
        topo = encode_link_state(ls)
        me = self.solver.my_node_name
        # EncodedPrefixCandidates exposes the exact candidate-array schema
        # the selector reads — no copy
        cands = encode_prefix_candidates(prefix_state, topo, area)
        sweep = LinkFailureSweep(topo, me)
        self._sweep = sweep
        self._selector = SweepRouteSelector(topo, me, cands, max_degree=sweep.D)
        self._topo = topo
        self._prefixes = cands.prefixes
        #: node-pair -> undirected link ids (PARALLEL links are distinct:
        #: link identity includes interfaces, link_state.py)
        self._pair_links = {}
        for i, link in enumerate(topo.links):
            self._pair_links.setdefault(
                frozenset((link.n1, link.n2)), []
            ).append(i)
        self._cache_key = key
        self.num_engine_builds += 1

    def run(
        self,
        link_failures: List[Tuple[str, str]],
        area_link_states,
        prefix_state,
        change_seq: int,
    ) -> Dict:
        """One device sweep over the given candidate failures; returns
        per-failure route deltas from this node's vantage."""
        self._engine_for(area_link_states, prefix_state, change_seq)
        me = self.solver.my_node_name
        lane_names = [
            neighbor for (_link, neighbor) in self._topo.root_out_edges(me)
        ]
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop

        fails = []
        resolved: List[Optional[object]] = []
        for n1, n2 in link_failures:
            lids = self._pair_links.get(frozenset((n1, n2)), [])
            if len(lids) == 1:
                resolved.append(lids[0])
                fails.append(lids[0])
            else:
                # 0 = unknown pair; >1 = parallel links, where failing
                # only one would mislead (traffic shifts to the survivor)
                resolved.append(None if not lids else len(lids))
                fails.append(-1)
        deltas = self._selector.run(
            self._sweep.run(np.asarray(fails, np.int32), fetch=False)
        )
        self.num_sweeps += 1

        def lanes_to_names(lane_row) -> List[str]:
            return [
                lane_names[i]
                for i in np.nonzero(lane_row)[0]
                if i < len(lane_names)
            ]

        base_valid = deltas.base_valid
        out = []
        for s, ((n1, n2), lid) in enumerate(zip(link_failures, resolved)):
            if lid is None:
                out.append({"link": [n1, n2], "error": "unknown link"})
                continue
            if fails[s] == -1:  # lid holds the parallel-link count
                out.append(
                    {
                        "link": [n1, n2],
                        "error": (
                            f"{lid} parallel links between pair; "
                            "single-link what-if would shift traffic to "
                            "the survivors — not supported"
                        ),
                    }
                )
                continue
            changes = []
            row = int(deltas.snap_row[s])
            if row != 0:
                p_idx, valid, metric, lanes = deltas.deltas_of_row(row)
                for k in range(len(p_idx)):
                    p = int(p_idx[k])
                    prefix = self._prefixes[p]
                    if prefix_is_v4(prefix) and not v4_ok:
                        continue
                    was, now = bool(base_valid[p]), bool(valid[k])
                    if was and not now:
                        kind = "removed"
                    elif now and not was:
                        kind = "added"
                    else:
                        kind = "rerouted"
                    changes.append(
                        {
                            "prefix": prefix,
                            "change": kind,
                            "old_nexthops": (
                                lanes_to_names(deltas.base_lanes[p])
                                if was
                                else []
                            ),
                            "new_nexthops": (
                                lanes_to_names(lanes[k]) if now else []
                            ),
                            "old_metric": (
                                float(deltas.base_metric[p]) if was else None
                            ),
                            "new_metric": (
                                float(metric[k]) if now else None
                            ),
                        }
                    )
            out.append(
                {
                    "link": [n1, n2],
                    "on_shortest_path_dag": bool(
                        self._sweep.on_dag_links()[lid]
                    ),
                    "routes_changed": len(changes),
                    "changes": changes,
                }
            )
        return {"eligible": True, "vantage": me, "failures": out}
