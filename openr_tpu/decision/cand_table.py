"""Incremental columnar candidate table: PrefixState → device arrays.

The reference recomputes only changed prefixes on prefix-only deltas
(Decision.cpp:908-952).  The device path needs the same property at the
ENCODING layer: re-flattening every (prefix, candidate) advertisement into
padded arrays on each debounce tick is O(P*C) Python and blows the
10-250ms budget at DecisionBenchmark scale (10k nodes x 1000
prefixes/node).  This table keeps the flattened columns *resident* across
rebuilds and applies per-prefix dirty updates:

  * metric columns ([cap, C] int32: drain/path-pref/source-pref/distance/
    min-nexthop) are topology-independent — a prefix churn touches only
    its own row
  * advertiser identity is stored as interned GLOBAL ids (node gid, area
    gid), so a topology re-encode (new symbol tables) never re-reads
    PrefixState: the per-area candidate ids (`cand_node`, `cand_area`,
    `cand_node_in_area`) are derived from the gid columns by vectorized
    numpy table lookups against the current EncodedMultiArea
  * row capacity and candidate width grow in buckets so downstream jit
    shapes stay cache-stable (SURVEY §7 hard-part 4)

Rows of deleted prefixes go on a free list and are reused; a free row is
all-invalid (`adv_gid == -1`) and therefore produces no route.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set

import numpy as np

from openr_tpu.ops.csr import EncodedMultiArea, bucket_for

ROW_BUCKETS = (
    64,
    256,
    1024,
    4096,
    16384,
    65536,
    262144,
    1048576,
    4194304,
    16777216,
)
CAND_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


@dataclasses.dataclass
class DerivedCandidates:
    """Per-EncodedMultiArea view of the table (numpy, [cap, C])."""

    cand_area: np.ndarray  # [cap, C] int32 area index (0 where not ok)
    cand_node: np.ndarray  # [cap, C] int32 id in own area (0 where not ok)
    cand_ok: np.ndarray  # [cap, C] bool
    drain_metric: np.ndarray  # [cap, C] int32
    path_pref: np.ndarray  # [cap, C] int32
    source_pref: np.ndarray  # [cap, C] int32
    distance: np.ndarray  # [cap, C] int32
    min_nexthop: np.ndarray  # [cap, C] int32 (0 = unset)
    cand_node_in_area: np.ndarray  # [cap, C, A] int32 (-1 = absent)


class CandidateTable:
    def __init__(
        self,
        row_buckets: Sequence[int] = ROW_BUCKETS,
        cand_buckets: Sequence[int] = CAND_BUCKETS,
    ) -> None:
        self.row_buckets = tuple(row_buckets)
        self.cand_buckets = tuple(cand_buckets)
        # interning (grow-only; survives topology re-encodes)
        self._node_gid: Dict[str, int] = {}
        self._gid_names: List[str] = []
        self._area_gid: Dict[str, int] = {}
        self._area_names: List[str] = []
        # rows
        self.pid: Dict[str, int] = {}
        self.row_prefix: List[Optional[str]] = []
        self._free: List[int] = []
        self.cap = 0
        self.C = self.cand_buckets[0]
        # columns [cap, C]
        self.adv_gid = np.full((0, self.C), -1, np.int32)
        self.adv_area = np.zeros((0, self.C), np.int32)
        self.drain = np.zeros((0, self.C), np.int32)
        self.pp = np.zeros((0, self.C), np.int32)
        self.sp = np.zeros((0, self.C), np.int32)
        self.dist = np.zeros((0, self.C), np.int32)
        self.minnh = np.zeros((0, self.C), np.int32)
        # derived-view cache
        self._derived: Optional[DerivedCandidates] = None
        self._derived_enc: Optional[EncodedMultiArea] = None
        self._derived_dirty_rows: Set[int] = set()
        self._full_synced = False

    # -- interning ---------------------------------------------------------

    def _gid(self, node: str) -> int:
        g = self._node_gid.get(node)
        if g is None:
            g = len(self._gid_names)
            self._node_gid[node] = g
            self._gid_names.append(node)
        return g

    def _agid(self, area: str) -> int:
        g = self._area_gid.get(area)
        if g is None:
            g = len(self._area_names)
            self._area_gid[area] = g
            self._area_names.append(area)
        return g

    # -- capacity management ----------------------------------------------

    def _grow_rows(self, need: int) -> None:
        new_cap = bucket_for(need, self.row_buckets)
        if new_cap <= self.cap:
            return
        pad = new_cap - self.cap

        def grow(a, fill):
            return np.concatenate(
                [a, np.full((pad, a.shape[1]), fill, a.dtype)]
            )

        self.adv_gid = grow(self.adv_gid, -1)
        self.adv_area = grow(self.adv_area, 0)
        self.drain = grow(self.drain, 0)
        self.pp = grow(self.pp, 0)
        self.sp = grow(self.sp, 0)
        self.dist = grow(self.dist, 0)
        self.minnh = grow(self.minnh, 0)
        self._free.extend(range(new_cap - 1, self.cap - 1, -1))
        self.row_prefix.extend([None] * pad)
        self.cap = new_cap
        self._derived = None  # shapes changed; regenerate view

    def _widen(self, need: int) -> None:
        new_c = bucket_for(need, self.cand_buckets)
        if new_c <= self.C:
            return
        pad = new_c - self.C

        def widen(a, fill):
            return np.concatenate(
                [a, np.full((a.shape[0], pad), fill, a.dtype)], axis=1
            )

        self.adv_gid = widen(self.adv_gid, -1)
        self.adv_area = widen(self.adv_area, 0)
        self.drain = widen(self.drain, 0)
        self.pp = widen(self.pp, 0)
        self.sp = widen(self.sp, 0)
        self.dist = widen(self.dist, 0)
        self.minnh = widen(self.minnh, 0)
        self.C = new_c
        self._derived = None

    # -- row encoding ------------------------------------------------------

    def _encode_row(self, r: int, entries) -> None:
        """Fill row r from one prefix's {(node, area) -> PrefixEntry} map.
        Candidate order is sorted (node, area) — deterministic, matching
        the scalar path's iteration for bestNodeArea recovery."""
        items = sorted(entries.items())
        if len(items) > self.C:
            if len(items) > self.cand_buckets[-1]:
                raise ValueError(
                    f"prefix with {len(items)} candidates exceeds the "
                    f"largest candidate bucket {self.cand_buckets[-1]}"
                )
            self._widen(len(items))
        self.adv_gid[r, :] = -1
        for c, ((node, area), entry) in enumerate(items):
            m = entry.metrics
            self.adv_gid[r, c] = self._gid(node)
            self.adv_area[r, c] = self._agid(area)
            self.drain[r, c] = m.drain_metric
            self.pp[r, c] = m.path_preference
            self.sp[r, c] = m.source_preference
            self.dist[r, c] = m.distance
            self.minnh[r, c] = entry.min_nexthop or 0
        self._derived_dirty_rows.add(r)

    def _clear_row(self, r: int) -> None:
        self.adv_gid[r, :] = -1
        self._derived_dirty_rows.add(r)

    # -- sync API ----------------------------------------------------------

    def full_sync(self, prefix_state) -> None:
        """Rebuild every row from PrefixState (initial build / fallback)."""
        all_prefixes = prefix_state.prefixes()
        self.pid.clear()
        self._free.clear()
        self._grow_rows(max(len(all_prefixes), 1))
        self.row_prefix = [None] * self.cap
        self.adv_gid[:, :] = -1
        # columnar fill: one pass building flat index/value lists, then a
        # single scatter per column — no per-cell numpy __setitem__
        rows: List[int] = []
        cols: List[int] = []
        v_gid: List[int] = []
        v_area: List[int] = []
        v_drain: List[int] = []
        v_pp: List[int] = []
        v_sp: List[int] = []
        v_dist: List[int] = []
        v_minnh: List[int] = []
        widest = 1
        for r, (prefix, entries) in enumerate(all_prefixes.items()):
            self.pid[prefix] = r
            self.row_prefix[r] = prefix
            items = sorted(entries.items())
            widest = max(widest, len(items))
            for c, ((node, area), entry) in enumerate(items):
                m = entry.metrics
                rows.append(r)
                cols.append(c)
                v_gid.append(self._gid(node))
                v_area.append(self._agid(area))
                v_drain.append(m.drain_metric)
                v_pp.append(m.path_preference)
                v_sp.append(m.source_preference)
                v_dist.append(m.distance)
                v_minnh.append(entry.min_nexthop or 0)
        if widest > self.C:
            if widest > self.cand_buckets[-1]:
                raise ValueError(
                    f"prefix with {widest} candidates exceeds the largest "
                    f"candidate bucket {self.cand_buckets[-1]}"
                )
            self._widen(widest)
        n = len(all_prefixes)
        self._free = list(range(self.cap - 1, n - 1, -1))
        if rows:
            ri = np.asarray(rows, np.int64)
            ci = np.asarray(cols, np.int64)
            self.adv_gid[ri, ci] = np.asarray(v_gid, np.int32)
            self.adv_area[ri, ci] = np.asarray(v_area, np.int32)
            self.drain[ri, ci] = np.asarray(v_drain, np.int32)
            self.pp[ri, ci] = np.asarray(v_pp, np.int32)
            self.sp[ri, ci] = np.asarray(v_sp, np.int32)
            self.dist[ri, ci] = np.asarray(v_dist, np.int32)
            self.minnh[ri, ci] = np.asarray(v_minnh, np.int32)
        self._derived = None
        self._full_synced = True

    def apply_dirty(self, prefix_state, changed: Iterable[str]) -> None:
        """Re-encode only the changed prefixes (add/update/delete)."""
        if not self._full_synced:
            self.full_sync(prefix_state)
            return
        all_prefixes = prefix_state.prefixes()
        for prefix in changed:
            entries = all_prefixes.get(prefix)
            r = self.pid.get(prefix)
            if entries:
                if r is None:
                    if not self._free:
                        self._grow_rows(self.cap + 1)
                    r = self._free.pop()
                    self.pid[prefix] = r
                    self.row_prefix[r] = prefix
                self._encode_row(r, entries)
            elif r is not None:
                del self.pid[prefix]
                self.row_prefix[r] = None
                self._clear_row(r)
                self._free.append(r)

    # -- derived view ------------------------------------------------------

    def derived(self, enc: EncodedMultiArea) -> DerivedCandidates:
        """Vectorized gid → per-area-id resolution for the current
        topology encoding.  Candidates advertised in unknown areas or by
        nodes absent from their area's graph come out cand_ok=False
        (scalar: unreachable, filtered before selection —
        SpfSolver.cpp:195-215)."""
        A = enc.num_areas
        G = len(self._gid_names)
        AG = len(self._area_names)
        cache = getattr(self, "_lookup_cache", None)
        if cache is not None and cache[0] is enc and cache[1] == (G, AG):
            gid_to_area_ids, area_gid_to_ai = cache[2], cache[3]
        else:
            gid_to_area_ids = np.full((G + 1, A), -1, np.int32)  # +1: -1 pad
            for ai, topo in enumerate(enc.topos):
                node_ids = topo.node_ids
                for g, name in enumerate(self._gid_names):
                    nid = node_ids.get(name)
                    if nid is not None:
                        gid_to_area_ids[g, ai] = nid
            area_gid_to_ai = np.full(AG + 1, -1, np.int32)
            for ai, a in enumerate(enc.areas):
                ag = self._area_gid.get(a)
                if ag is not None:
                    area_gid_to_ai[ag] = ai
            self._lookup_cache = (enc, (G, AG), gid_to_area_ids, area_gid_to_ai)

        if self._derived is not None and self._derived_enc is enc:
            rows = sorted(self._derived_dirty_rows)
            if not rows:
                return self._derived
            ri = np.asarray(rows, np.int64)
            d = self._derived
            self._fill_derived(
                d, gid_to_area_ids, area_gid_to_ai, ri
            )
            self._derived_dirty_rows.clear()
            return d

        d = DerivedCandidates(
            cand_area=np.zeros((self.cap, self.C), np.int32),
            cand_node=np.zeros((self.cap, self.C), np.int32),
            cand_ok=np.zeros((self.cap, self.C), bool),
            drain_metric=self.drain,
            path_pref=self.pp,
            source_pref=self.sp,
            distance=self.dist,
            min_nexthop=self.minnh,
            cand_node_in_area=np.full((self.cap, self.C, A), -1, np.int32),
        )
        self._fill_derived(d, gid_to_area_ids, area_gid_to_ai, None)
        self._derived = d
        self._derived_enc = enc
        self._derived_dirty_rows.clear()
        return d

    def _fill_derived(
        self, d, gid_to_area_ids, area_gid_to_ai, rows: Optional[np.ndarray]
    ) -> None:
        sl = slice(None) if rows is None else rows
        gid = self.adv_gid[sl]  # [R, C]
        agid = self.adv_area[sl]
        present = gid >= 0
        ai = np.where(present, area_gid_to_ai[agid], -1)  # [R, C]
        # node id in own area (gid -1 → lookup row G, all -1)
        nid_by_area = gid_to_area_ids[np.where(present, gid, -1)]  # [R, C, A]
        nid = np.take_along_axis(
            nid_by_area, np.maximum(ai, 0)[:, :, None], axis=2
        )[:, :, 0]
        ok = present & (ai >= 0) & (nid >= 0)
        d.cand_area[sl] = np.where(ok, ai, 0)
        d.cand_node[sl] = np.where(ok, nid, 0)
        d.cand_ok[sl] = ok
        d.cand_node_in_area[sl] = np.where(
            present[:, :, None], nid_by_area, -1
        )
        # metric columns are shared references (self.drain etc.) — no copy

    # -- introspection -----------------------------------------------------

    def rows_for(self, prefixes: Iterable[str]) -> List[int]:
        return [self.pid[p] for p in prefixes if p in self.pid]

    @property
    def num_prefixes(self) -> int:
        return len(self.pid)
