"""LinkState: per-area topology graph + SPF (scalar reference core).

Faithful Python equivalent of the reference's pure compute core
(openr/decision/LinkState.{h,cpp}) — the piece the TPU kernel replaces.
This scalar implementation is the semantic oracle: the batched JAX kernels
in ``openr_tpu.ops`` are validated against it, and it remains the fallback
path for hosts without accelerators.

Key semantics preserved (citations into /root/reference):
  * Links exist only when BOTH directions advertise matching adjacencies
    (maybeMakeLink, LinkState.cpp:407-423).
  * Hard-drain: node overload bit → node is reachable but never transits
    (runSpf, LinkState.cpp:739-752); interface overload on either side → link
    unusable (Link::isUp, LinkState.h:118-121).
  * Soft-drain: per-direction metric override; SPF uses the MAX of the two
    directional metrics (LinkState.cpp:780-790 comment block).
  * All-shortest-paths: NodeSpfResult carries the full nexthop set (first
    hops at the root) and predecessor path-links (LinkState.h:290-345).
  * adjOnlyUsedByOtherNode: adjacency usable only by the initializing
    neighbor (adjUsable, LinkState.h:18-40).
  * SPF + k-shortest-path results memoized until topology changes
    (LinkState.h:346-390, cleared in updateAdjacencyDatabase).
  * getKthPaths: edge-disjoint k-th paths by re-running SPF ignoring links
    used by paths 1..k-1 (LinkState.cpp:675-699); traceOnePath recursive
    path extraction (LinkState.cpp:227-247).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from openr_tpu.types import Adjacency, AdjacencyDatabase

INF = float("inf")


def _adj_usable(adj: Adjacency, my_node_name: str) -> bool:
    """adjUsable (LinkState.h:18-40): if adj_only_used_by_other_node is set,
    only the *other* node of that adjacency may use it."""
    if not adj.adj_only_used_by_other_node:
        return True
    return adj.other_node_name == my_node_name


class Link:
    """A bidirectional link (openr/decision/LinkState.h:64-260).

    Holds per-direction metric/overload/adj-label/weight/nexthop-addr; the
    canonical identity is the ordered (node, iface) pair tuple.
    """

    __slots__ = (
        "area",
        "n1",
        "if1",
        "n2",
        "if2",
        "metric1",
        "metric2",
        "overload1",
        "overload2",
        "usable",
        "adj_label1",
        "adj_label2",
        "weight1",
        "weight2",
        "nh_v4_1",
        "nh_v4_2",
        "nh_v6_1",
        "nh_v6_2",
        "_key",
    )

    def __init__(
        self,
        area: str,
        node1: str,
        adj1: Adjacency,
        node2: str,
        adj2: Adjacency,
        usable: bool = True,
    ) -> None:
        self.area = area
        # normalize: n1 is the lexicographically first (node, iface) end,
        # mirroring the reference's orderedNames_ so identity is symmetric
        if (node1, adj1.if_name) <= (node2, adj2.if_name):
            a, an, b, bn = adj1, node1, adj2, node2
        else:
            a, an, b, bn = adj2, node2, adj1, node1
        self.n1, self.if1 = an, a.if_name
        self.n2, self.if2 = bn, b.if_name
        # metricN / overloadN describe the direction *from* nN
        self.metric1, self.metric2 = a.metric, b.metric
        self.overload1, self.overload2 = a.is_overloaded, b.is_overloaded
        self.adj_label1, self.adj_label2 = a.adj_label, b.adj_label
        self.weight1, self.weight2 = a.weight, b.weight
        # adjacency advertised BY nN carries the address of the *other* end,
        # which is what nN uses as its nexthop over this link
        self.nh_v4_1, self.nh_v6_1 = a.next_hop_v4, a.next_hop_v6
        self.nh_v4_2, self.nh_v6_2 = b.next_hop_v4, b.next_hop_v6
        self.usable = usable
        self._key = (self.n1, self.if1, self.n2, self.if2)

    # -- identity ----------------------------------------------------------

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return self._key

    def __hash__(self) -> int:
        return hash(self._key)

    def __eq__(self, other) -> bool:
        return isinstance(other, Link) and self._key == other._key

    def __lt__(self, other: "Link") -> bool:
        return self._key < other._key

    def __repr__(self) -> str:
        return f"Link({self.n1}:{self.if1} <-> {self.n2}:{self.if2})"

    def directional_str(self, from_node: str) -> str:
        o = self.get_other_node_name(from_node)
        return f"{from_node}:{self.get_iface_from_node(from_node)} -> {o}"

    # -- accessors (LinkState.h:118-240) -----------------------------------

    def is_up(self) -> bool:
        return (not self.overload1) and (not self.overload2) and self.usable

    def get_other_node_name(self, node: str) -> str:
        if node == self.n1:
            return self.n2
        if node == self.n2:
            return self.n1
        raise ValueError(node)

    def _side(self, node: str) -> int:
        if node == self.n1:
            return 1
        if node == self.n2:
            return 2
        raise ValueError(node)

    def get_iface_from_node(self, node: str) -> str:
        return self.if1 if self._side(node) == 1 else self.if2

    def get_metric_from_node(self, node: str) -> int:
        return self.metric1 if self._side(node) == 1 else self.metric2

    def set_metric_from_node(self, node: str, metric: int) -> bool:
        """Returns True if the topology changed (reference setMetricFromNode)."""
        if self._side(node) == 1:
            changed = self.metric1 != metric
            self.metric1 = metric
        else:
            changed = self.metric2 != metric
            self.metric2 = metric
        return changed

    def get_max_metric(self) -> int:
        """Soft-drain rule: SPF uses max of both directions
        (LinkState.cpp:789)."""
        return max(self.metric1, self.metric2)

    def get_overload_from_node(self, node: str) -> bool:
        return self.overload1 if self._side(node) == 1 else self.overload2

    def set_overload_from_node(self, node: str, overloaded: bool) -> bool:
        was_up = self.is_up()
        if self._side(node) == 1:
            self.overload1 = overloaded
        else:
            self.overload2 = overloaded
        return was_up != self.is_up()

    def get_adj_label_from_node(self, node: str) -> int:
        return self.adj_label1 if self._side(node) == 1 else self.adj_label2

    def get_weight_from_node(self, node: str) -> int:
        return self.weight1 if self._side(node) == 1 else self.weight2

    def get_nh_v4_from_node(self, node: str) -> str:
        return self.nh_v4_1 if self._side(node) == 1 else self.nh_v4_2

    def get_nh_v6_from_node(self, node: str) -> str:
        return self.nh_v6_1 if self._side(node) == 1 else self.nh_v6_2


@dataclass
class NodeSpfResult:
    """SPF result for one destination (LinkState.h:290-345): distance,
    first-hop neighbor set at the root, and predecessor links for path
    tracing."""

    metric: float
    next_hops: Set[str] = field(default_factory=set)
    #: (link, prev_node) pairs on shortest paths into this node
    path_links: List[Tuple[Link, str]] = field(default_factory=list)

    def reset(self, new_metric: float) -> None:
        self.metric = new_metric
        self.next_hops.clear()
        self.path_links.clear()


SpfResult = Dict[str, NodeSpfResult]
Path = List[Link]


@dataclass
class LinkStateChange:
    """What an LSDB update changed (LinkState.h:396-430)."""

    topology_changed: bool = False
    link_attributes_changed: bool = False
    node_label_changed: bool = False
    added_links: List[Link] = field(default_factory=list)
    #: usable links that went DOWN in this update (a clean up->down
    #: flip, or an up link leaving the LSDB — one side withdrawing its
    #: adjacency).  The protection tier's failure classifier reads this:
    #: a tick whose ONLY topology change is down_links is patch-servable
    down_links: List[Link] = field(default_factory=list)
    #: any OTHER SPF-relevant change (link up/add, metric shift,
    #: overload/drain flip, node-metric increment, node membership) —
    #: such a tick is never served from a protection patch
    other_topology_change: bool = False


class LinkState:
    """Per-area link-state graph with memoized SPF
    (openr/decision/LinkState.h:270-600)."""

    def __init__(self, area: str, my_node_name: str = "") -> None:
        self.area = area
        self.my_node_name = my_node_name
        self._adj_dbs: Dict[str, AdjacencyDatabase] = {}
        self._link_map: Dict[str, Set[Link]] = {}
        self._all_links: Set[Link] = set()
        self._node_overloads: Dict[str, bool] = {}
        self._node_metric_increments: Dict[str, int] = {}
        # memoization (invalidated on topology change)
        self._spf_results: Dict[Tuple[str, bool], SpfResult] = {}
        self._kth_path_results: Dict[Tuple[str, str, int], List[Path]] = {}
        self.num_spf_runs = 0
        #: bumped on every SPF-relevant change — downstream encoders (the
        #: device CSR bridge) key their caches on it, so prefix-only
        #: rebuilds skip topology re-encoding entirely
        self.topology_seq = 0
        self._all_links_cache: Optional[Tuple[int, List[Link]]] = None
        #: per-node sorted adjacency, invalidated structurally on
        #: add/remove — run_spf iterates it so path_links order (and thus
        #: the greedy KSP2 trace) is deterministic across runs, which the
        #: device-backed k-path reconstruction reproduces exactly
        self._ordered_links_cache: Dict[str, List[Link]] = {}

    # -- introspection -----------------------------------------------------

    def has_node(self, node: str) -> bool:
        return node in self._link_map or node in self._adj_dbs

    def num_links(self) -> int:
        return len(self._all_links)

    def num_nodes(self) -> int:
        return len(self._link_map)

    def get_adjacency_databases(self) -> Dict[str, AdjacencyDatabase]:
        return self._adj_dbs

    def is_node_overloaded(self, node: str) -> bool:
        return self._node_overloads.get(node, False)

    def get_node_metric_increment(self, node: str) -> int:
        return self._node_metric_increments.get(node, 0)

    def links_from_node(self, node: str) -> Set[Link]:
        return self._link_map.get(node, set())

    def clear_spf_memoization(self) -> None:
        """Drop memoized SPF/k-path results without touching the graph —
        benchmarking hook for measuring cold solves (the memo is otherwise
        invalidated only by topology changes)."""
        self._spf_results.clear()
        self._kth_path_results.clear()

    def all_links(self) -> List[Link]:
        """All undirected links, in canonical order (stable across calls).
        Cached per topology_seq — sorting a 4096-node LSDB's link set costs
        ~20ms, which the encoder would otherwise pay on every rebuild."""
        cached = self._all_links_cache
        if cached is not None and cached[0] == self.topology_seq:
            return cached[1]
        links = sorted(self._all_links)
        self._all_links_cache = (self.topology_seq, links)
        return links

    def ordered_links_from_node(self, node: str) -> List[Link]:
        cached = self._ordered_links_cache.get(node)
        if cached is None:
            cached = sorted(self._link_map.get(node, set()))
            self._ordered_links_cache[node] = cached
        return cached

    # -- link construction (LinkState.cpp:407-438) -------------------------

    def _maybe_make_link(self, node: str, adj: Adjacency) -> Optional[Link]:
        """Only bidirectionally-confirmed adjacencies become links."""
        other_db = self._adj_dbs.get(adj.other_node_name)
        if other_db is None:
            return None
        for other_adj in other_db.adjacencies:
            if (
                other_adj.other_node_name == node
                and adj.other_if_name == other_adj.if_name
                and adj.if_name == other_adj.other_if_name
            ):
                usable = _adj_usable(adj, self.my_node_name) and _adj_usable(
                    other_adj, self.my_node_name
                )
                return Link(
                    self.area, node, adj, adj.other_node_name, other_adj, usable
                )
        return None

    def _ordered_link_set(self, adj_db: AdjacencyDatabase) -> List[Link]:
        links = []
        for adj in adj_db.adjacencies:
            link = self._maybe_make_link(adj_db.this_node_name, adj)
            if link is not None:
                links.append(link)
        links.sort()
        return links

    def _add_link(self, link: Link) -> None:
        self._link_map.setdefault(link.n1, set()).add(link)
        self._link_map.setdefault(link.n2, set()).add(link)
        self._all_links.add(link)
        # a DOWN link joining/leaving doesn't set topology_changed (no SPF
        # impact), so invalidate the ordered-list caches structurally
        self._all_links_cache = None
        self._ordered_links_cache.pop(link.n1, None)
        self._ordered_links_cache.pop(link.n2, None)

    def _remove_link(self, link: Link) -> None:
        self._link_map.get(link.n1, set()).discard(link)
        self._link_map.get(link.n2, set()).discard(link)
        self._all_links.discard(link)
        self._all_links_cache = None
        self._ordered_links_cache.pop(link.n1, None)
        self._ordered_links_cache.pop(link.n2, None)

    def _update_node_overloaded(self, node: str, overloaded: bool) -> bool:
        prior = self._node_overloads.get(node)
        self._node_overloads[node] = overloaded
        # a brand-new node or an unchanged bit is not a topology change
        return prior is not None and prior != overloaded

    # -- LSDB updates (LinkState.cpp:441-643) ------------------------------

    def update_adjacency_database(
        self, new_db: AdjacencyDatabase, in_initialization: bool = False
    ) -> LinkStateChange:
        assert new_db.area == self.area or not new_db.area, (
            f"area mismatch {new_db.area} != {self.area}"
        )
        change = LinkStateChange()
        node = new_db.this_node_name
        prior_db = self._adj_dbs.get(node, AdjacencyDatabase(node, area=self.area))
        self._adj_dbs[node] = new_db

        if self._update_node_overloaded(node, new_db.is_overloaded):
            change.topology_changed = True
            change.other_topology_change = True
        if prior_db.node_metric_increment_val != new_db.node_metric_increment_val:
            change.topology_changed = True
            change.other_topology_change = True
        self._node_metric_increments[node] = new_db.node_metric_increment_val
        change.node_label_changed = prior_db.node_label != new_db.node_label

        old_links = self.ordered_links_from_node(node)
        new_links = self._ordered_link_set(new_db)

        # ordered merge of old/new link sets → adds, removes, attribute diffs
        # (LinkState.cpp:492-637)
        i = j = 0
        while i < len(new_links) or j < len(old_links):
            if i < len(new_links) and (
                j >= len(old_links) or new_links[i] < old_links[j]
            ):
                nl = new_links[i]
                if nl.is_up():
                    change.topology_changed = True
                    change.other_topology_change = True
                self._add_link(nl)
                change.added_links.append(nl)
                i += 1
                continue
            if j < len(old_links) and (
                i >= len(new_links) or old_links[j] < new_links[i]
            ):
                ol = old_links[j]
                if ol.is_up():
                    change.topology_changed = True
                    change.down_links.append(ol)
                self._remove_link(ol)
                j += 1
                continue
            # same link identity: diff attributes in place on the live object
            nl, ol = new_links[i], old_links[j]
            if nl.get_metric_from_node(node) != ol.get_metric_from_node(node):
                if ol.set_metric_from_node(
                    node, nl.get_metric_from_node(node)
                ):
                    change.topology_changed = True
                    change.other_topology_change = True
            if nl.is_up() != ol.is_up():
                if ol.is_up():
                    change.down_links.append(ol)
                else:
                    change.other_topology_change = True
                ol.usable = nl.usable
                change.topology_changed = True
            if nl.get_overload_from_node(node) != ol.get_overload_from_node(node):
                # simplex overloads unsupported: only an up<->down flip is a
                # topology change (Link::setOverloadFromNode, LinkState.cpp:159)
                was_up = ol.is_up()
                ol.set_overload_from_node(node, nl.get_overload_from_node(node))
                if was_up != ol.is_up():
                    # operator drain, not a failure: never patch-served
                    change.topology_changed = True
                    change.other_topology_change = True
            if nl.get_adj_label_from_node(node) != ol.get_adj_label_from_node(node):
                change.link_attributes_changed = True
                if ol._side(node) == 1:
                    ol.adj_label1 = nl.get_adj_label_from_node(node)
                else:
                    ol.adj_label2 = nl.get_adj_label_from_node(node)
            if nl.get_weight_from_node(node) != ol.get_weight_from_node(node):
                change.link_attributes_changed = True
                if ol._side(node) == 1:
                    ol.weight1 = nl.get_weight_from_node(node)
                else:
                    ol.weight2 = nl.get_weight_from_node(node)
            if nl.get_nh_v4_from_node(node) != ol.get_nh_v4_from_node(
                node
            ) or nl.get_nh_v6_from_node(node) != ol.get_nh_v6_from_node(node):
                change.link_attributes_changed = True
                if ol._side(node) == 1:
                    ol.nh_v4_1, ol.nh_v6_1 = (
                        nl.get_nh_v4_from_node(node),
                        nl.get_nh_v6_from_node(node),
                    )
                else:
                    ol.nh_v4_2, ol.nh_v6_2 = (
                        nl.get_nh_v4_from_node(node),
                        nl.get_nh_v6_from_node(node),
                    )
            i += 1
            j += 1

        if change.topology_changed:
            self._spf_results.clear()
            self._kth_path_results.clear()
            self.topology_seq += 1
        return change

    def delete_adjacency_database(self, node: str) -> LinkStateChange:
        change = LinkStateChange()
        if node not in self._adj_dbs:
            return change
        for link in list(self._link_map.get(node, set())):
            self._remove_link(link)
        self._link_map.pop(node, None)
        self._node_overloads.pop(node, None)
        self._node_metric_increments.pop(node, None)
        del self._adj_dbs[node]
        self._spf_results.clear()
        self._kth_path_results.clear()
        self.topology_seq += 1
        change.topology_changed = True
        # a node leaving the LSDB fails ALL its links at once — outside
        # the single-link protection envelope by construction
        change.other_topology_change = True
        return change

    # -- SPF (LinkState.cpp:721-807) ---------------------------------------

    def run_spf(
        self,
        root: str,
        use_link_metric: bool = True,
        links_to_ignore: FrozenSet[Link] = frozenset(),
    ) -> SpfResult:
        """Dijkstra from `root` with all-shortest-paths nexthop tracking.

        Nexthops are first-hop *neighbor node names* at the root; every
        equal-cost predecessor contributes its nexthop set (the reference's
        addNextHops accumulation).
        """
        self.num_spf_runs += 1
        result: SpfResult = {}
        # pending nodes: name -> NodeSpfResult being refined; heap for order
        pending: Dict[str, NodeSpfResult] = {root: NodeSpfResult(0)}
        heap: List[Tuple[float, str]] = [(0, root)]
        while heap:
            metric, name = heapq.heappop(heap)
            node_res = pending.get(name)
            if node_res is None or name in result or metric > node_res.metric:
                continue  # stale heap entry
            del pending[name]
            result[name] = node_res

            # Node hard-drain: record reachability, never transit
            # (LinkState.cpp:739-752)
            if self.is_node_overloaded(name) and name != root:
                continue

            for link in self.ordered_links_from_node(name):
                other = link.get_other_node_name(name)
                if (not link.is_up()) or other in result or link in links_to_ignore:
                    continue
                metric_over_link = link.get_max_metric() if use_link_metric else 1
                cand = node_res.metric + metric_over_link
                other_res = pending.get(other)
                if other_res is None:
                    other_res = pending[other] = NodeSpfResult(cand)
                    heapq.heappush(heap, (cand, other))
                if other_res.metric >= cand:
                    if other_res.metric > cand:
                        other_res.reset(cand)
                        heapq.heappush(heap, (cand, other))
                    other_res.path_links.append((link, name))
                    other_res.next_hops.update(node_res.next_hops)
                    if not other_res.next_hops:
                        # directly connected to root
                        other_res.next_hops.add(other)
        return result

    def get_spf_result(self, root: str, use_link_metric: bool = True) -> SpfResult:
        key = (root, use_link_metric)
        if key not in self._spf_results:
            self._spf_results[key] = self.run_spf(root, use_link_metric)
        return self._spf_results[key]

    def get_metric_from_a_to_b(
        self, a: str, b: str, use_link_metric: bool = True
    ) -> Optional[float]:
        if a == b:
            return 0
        res = self.get_spf_result(a, use_link_metric)
        if b in res:
            return res[b].metric
        return None

    # -- k-shortest edge-disjoint paths (LinkState.cpp:653-703) ------------

    def has_kth_paths(self, src: str, dest: str, k: int) -> bool:
        return (src, dest, k) in self._kth_path_results

    def seed_kth_paths(
        self, src: str, dest: str, k: int, paths: List[Path]
    ) -> None:
        """Install externally-computed k-th paths into the memo (invalidated
        on topology change like every memoized result).  Used by the device
        backend: the expensive masked re-solves run batched on the TPU and
        the traced paths are seeded here, so ``get_kth_paths`` — and thus
        the whole scalar KSP2 selection chain — never runs host Dijkstra.
        """
        self._kth_path_results[(src, dest, k)] = paths

    def get_kth_paths(self, src: str, dest: str, k: int) -> List[Path]:
        assert k >= 1
        key = (src, dest, k)
        if key not in self._kth_path_results:
            links_to_ignore: Set[Link] = set()
            for i in range(1, k):
                for path in self.get_kth_paths(src, dest, i):
                    links_to_ignore.update(path)
            res = (
                self.get_spf_result(src, True)
                if not links_to_ignore
                else self.run_spf(src, True, frozenset(links_to_ignore))
            )
            paths: List[Path] = []
            if dest in res:
                visited: Set[Link] = set()
                path = self._trace_one_path(src, dest, res, visited)
                while path:
                    paths.append(path)
                    path = self._trace_one_path(src, dest, res, visited)
            self._kth_path_results[key] = paths
        return self._kth_path_results[key]

    def _trace_one_path(
        self, src: str, dest: str, result: SpfResult, links_to_ignore: Set[Link]
    ) -> Optional[Path]:
        """Extract one not-yet-traced path from the shortest-path DAG
        (traceOnePath, LinkState.cpp:227-247).  Returns None when exhausted;
        [] when src == dest."""
        if src == dest:
            return []
        for link, prev_node in result[dest].path_links:
            if link in links_to_ignore:
                continue
            links_to_ignore.add(link)
            sub = self._trace_one_path(src, prev_node, result, links_to_ignore)
            if sub is not None:
                sub.append(link)
                return sub
        return None

    @staticmethod
    def path_a_in_path_b(a: Path, b: Path) -> bool:
        """True if path A appears as a contiguous ordered sub-path of B
        (LinkState.h:483-503)."""
        if len(a) > len(b):
            return False
        for i in range(len(b) - len(a) + 1):
            if all(a[j] == b[i + j] for j in range(len(a))):
                return True
        return False
