"""Decision — LSDB consumption, debounced route rebuild, RIB publication.

Reference: openr/decision/Decision.{h,cpp}:
  * consumes KvStore publications (via the Dispatcher, ``adj:`` +
    ``prefix:`` keys) → per-area LinkState + global PrefixState
    (updateKeyInLsdb/deleteKeyFromLsdb, Decision.cpp:711-820)
  * debounced rebuild (AsyncDebounce 10–250 ms, Decision.cpp:114-120)
  * initialization gating: the first build waits for KVSTORE_SYNCED +
    static routes, force-unblocked after unblock_initial_routes_ms
    (Decision.cpp:963-1011); the first publication is FULL_SYNC, then
    incremental deltas
  * static routes from PrefixManager (staticRouteUpdatesQueue)
  * RibPolicy application before publishing + TTL'd persistence
    (Decision.cpp:634-708, 917-950)
  * PerfEvents breadcrumbs carried LSDB → RIB for convergence tracing
  * RIB_COMPUTED initialization event after the first build

The compute itself runs behind a DecisionBackend (scalar oracle or TPU
batched kernels) — the seam BASELINE.json pins at the plugin boundary.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Set

from openr_tpu import constants as C
from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.common.utils import AsyncDebounce
from openr_tpu.config import DecisionConfig
from openr_tpu.decision.backend import DecisionBackend, ScalarBackend
from openr_tpu.decision.link_state import LinkState
from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    DecisionRouteUpdateType,
)
from openr_tpu.decision.rib_policy import RibPolicy
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.messaging.queue import RQueue, ReplicateQueue
from openr_tpu.types import (
    AdjacencyDatabase,
    InitializationEvent,
    PerfEvents,
    PrefixDatabase,
    Publication,
    parse_adj_key,
    parse_prefix_key,
)


def deserialize_adj_db(data: bytes) -> AdjacencyDatabase:
    """Format-sniffing (JSON or the reference's thrift-compact bytes —
    openr_tpu.lsdb_codec), so Decision consumes floods from either
    encoding, including a reference node's."""
    from openr_tpu.lsdb_codec import deserialize_adj_db as _de

    return _de(data)


def deserialize_prefix_db(data: bytes) -> PrefixDatabase:
    from openr_tpu.lsdb_codec import deserialize_prefix_db as _de

    return _de(data)


#: process-wide latch: the long-lived-heap freeze happens once no matter
#: how many Decision actors share the interpreter
_GC_FROZEN = False


class Decision(Actor):
    def __init__(
        self,
        node_name: str,
        clock: Clock,
        config: DecisionConfig,
        route_updates_queue: ReplicateQueue,
        kv_store_updates_reader: Optional[RQueue] = None,
        static_routes_reader: Optional[RQueue] = None,
        backend: Optional[DecisionBackend] = None,
        solver: Optional[SpfSolver] = None,
        initialization_cb: Optional[Callable[[InitializationEvent], None]] = None,
        counters: Optional[CounterMap] = None,
        rib_policy_file: str = "",
        tracer=None,
    ) -> None:
        super().__init__("decision", clock, counters)
        from openr_tpu.tracing import disabled_tracer

        self.tracer = tracer if tracer is not None else disabled_tracer()
        self.node_name = node_name
        self.config = config
        self.route_updates_queue = route_updates_queue
        self.kv_store_updates_reader = kv_store_updates_reader
        self.static_routes_reader = static_routes_reader
        self.solver = solver or SpfSolver(node_name)
        self.backend = backend or ScalarBackend(self.solver)
        self.initialization_cb = initialization_cb
        self.rib_policy_file = rib_policy_file
        self.area_link_states: Dict[str, LinkState] = {}
        self.prefix_state = PrefixState()
        self.route_db = DecisionRouteDb()
        self.rib_policy: Optional[RibPolicy] = None
        self.pending_perf_events: Optional[PerfEvents] = None
        #: trace context of the newest LSDB change awaiting the debounced
        #: rebuild (the debounce coalesces; the span tree reflects the
        #: LAST event, matching pending_perf_events semantics)
        self.pending_trace_ctx = None
        # initialization gating (Decision.cpp:963-1011)
        self._kvstore_synced = False
        self._unblocked = False
        self._first_build_done = False
        #: cold-boot GC pause active (see _on_publication); always
        #: released by _end_boot_gc_window or stop()
        self._boot_gc_paused = False
        self._rebuild_pending = False
        # pending-delta accumulation between debounced rebuilds
        # (DecisionPendingUpdates, Decision.h:40-108): prefix-only deltas
        # drive per-prefix incremental recompute (Decision.cpp:908-952)
        self._pending_prefix_changes: Set[str] = set()
        self._pending_topo_changed = False
        #: a pending topology change is STRUCTURAL (a node, area or
        #: LINK entered/left the LSDB — the membership-churn class a
        #: rolling restart, autoscaling event or adjacency withdrawal
        #: produces) rather than a perturbation (weight/up-down flips
        #: on an unchanged membership, overload/drain flips).
        #: Perturbation ticks warm-start via the O(links) encode patch
        #: (ISSUE 9); structural ticks warm-start via the slot-stable
        #: encode (tombstones + free-list) and the generation-delta
        #: reset frontier (ISSUE 12).
        self._pending_topo_structural = False
        self._pending_force_full = False
        #: fast-reroute protection tier (a ProtectionService, wired by
        #: the daemon when protection_config.enabled; None otherwise)
        self.protection = None
        #: sorted (n1, n2) pairs the un-rebuilt LSDB window reported
        #: DOWN, and whether it carried ANY other topology change —
        #: the protection classifier's inputs, reset with the other
        #: pending-delta state at rebuild time
        self._pending_down_pairs: Set[tuple] = set()
        self._pending_other_change = False
        #: an applied-but-unconfirmed protection patch: what the FIB
        #: currently holds on top of route_db, awaiting the confirming
        #: warm solve ({"generation", "entries", "deletes"})
        self._frr_outstanding: Optional[dict] = None
        self._last_policy_active = False
        #: bumped on every LSDB change AND every RibPolicy set/clear —
        #: keys the fleet-RIB / what-if table caches and the serving
        #: plane's content-addressed result cache.  A policy flip between
        #: two identical-LSDB queries MUST invalidate those caches (the
        #: computed-result generation is (LSDB, policy), not LSDB alone)
        self._change_seq = 0
        #: serving-plane invalidation hooks, called with the new change
        #: seq whenever the computed-result generation moves; entries
        #: are (priority, registration index, fn) and fire in ascending
        #: order — see add_generation_listener
        self._generation_listeners: List[tuple] = []
        self._fleet_engine = None
        self._whatif_engine = None
        self._whatif_multi_engine = None
        self._whatif_native_engine = None
        self._whatif_generic_engine = None
        self._whatif_device_build_engine = None
        self._whatif_rt_ms = None
        self._debounce = AsyncDebounce(
            self,
            config.debounce_min_ms / 1000.0,
            config.debounce_max_ms / 1000.0,
            self._rebuild_routes,
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.kv_store_updates_reader is not None:
            self.spawn_queue_loop(
                self.kv_store_updates_reader, self._on_publication, "decision.kv"
            )
        if self.static_routes_reader is not None:
            self.spawn_queue_loop(
                self.static_routes_reader, self._on_static_routes, "decision.static"
            )
        self._load_rib_policy()
        # forced unblock of the initial build (unblock_initial_routes_ms)
        self.schedule(
            self.config.unblock_initial_routes_ms / 1000.0, self._force_unblock
        )

    async def stop(self) -> None:
        if self._boot_gc_paused:
            # never leave the process with the collector off (daemon
            # shut down before the first build completed)
            import gc

            self._boot_gc_paused = False
            gc.enable()
        await super().stop()

    def on_initialization_event(self, ev: InitializationEvent) -> None:
        """Wired by the daemon: KVSTORE_SYNCED gates the initial build."""
        if ev == InitializationEvent.KVSTORE_SYNCED:
            self._kvstore_synced = True
            self._maybe_unblock()

    def _maybe_unblock(self) -> None:
        if self._unblocked or not self._kvstore_synced:
            return
        self._unblocked = True
        if self._rebuild_pending or not self._first_build_done:
            self._debounce()

    def _force_unblock(self) -> None:
        if not self._unblocked:
            self.counters.bump("decision.forced_initial_unblock")
            self._unblocked = True
            self._debounce()

    # -- LSDB updates (processPublication, Decision.cpp:822) ---------------

    def _get_link_state(self, area: str) -> LinkState:
        if area not in self.area_link_states:
            self.area_link_states[area] = LinkState(area, self.node_name)
        return self.area_link_states[area]

    #: publications at/above this many prefix keys take the native bulk
    #: decode path (below it, batch setup costs more than it saves)
    BULK_INGEST_MIN = 32

    def _on_publication(self, pub: Publication) -> None:
        if len(pub.key_vals) >= self.BULK_INGEST_MIN:
            # large publication (cold boot / areawide churn): gen-2
            # collections re-scan the ever-growing LSDB heap (measured
            # 2x ingest slowdown at 409,600 prefixes with GC running).
            # During COLD BOOT (before the first build) the pause spans
            # the whole ingest window — re-enabling between publications
            # lets gen-2 scans of the growing, not-yet-frozen LSDB eat
            # the win right back; the window ends (collect + freeze +
            # re-enable) when the first large build completes, and the
            # forced-unblock timer bounds it.  Steady-state large
            # publications pause per-batch only.
            import gc

            if not self._first_build_done:
                if not self._boot_gc_paused and gc.isenabled():
                    gc.disable()
                    self._boot_gc_paused = True
                self._on_publication_inner(pub)
                return
            from openr_tpu.common.utils import gc_paused

            with gc_paused():
                self._on_publication_inner(pub)
            return
        self._on_publication_inner(pub)

    def _on_publication_inner(self, pub: Publication) -> None:
        # the generation this publication transitions FROM — the
        # identity a protection patch must have been minted at
        prev_key = (
            self.generation_key() if self.protection is not None else None
        )
        changed = False
        area = pub.area
        if pub.trace_ctx is not None:
            # flooding-metadata context; an adj payload below may replace
            # it with the origin-rooted one embedded in the LSDB value
            self.pending_trace_ctx = pub.trace_ctx
        bulk_items = None
        if len(pub.key_vals) >= self.BULK_INGEST_MIN:
            from openr_tpu.decision.ingest import get_bulk_decoder

            if get_bulk_decoder() is not None:
                bulk_items = []
        for key, value in pub.key_vals.items():
            if value.value is None:
                continue  # ttl-refresh only
            if bulk_items is not None and key.startswith(C.PREFIX_DB_MARKER):
                bulk_items.append((key, value.value))
                continue
            changed |= self._update_key(area, key, value.value)
        if bulk_items:
            changed |= self._bulk_update_prefix_keys(area, bulk_items)
        for key in pub.expired_keys:
            changed |= self._delete_key(area, key)
        if changed:
            self.counters.bump("decision.lsdb_updates")
            self._bump_generation()
            self._rebuild_pending = True
            if prev_key is not None:
                self._maybe_apply_protection(prev_key)
            if self._unblocked:
                self._debounce()

    def _bump_generation(self) -> None:
        """Advance the computed-result generation and notify the serving
        plane so cached results from the previous generation are never
        served again (the rebuild-path invalidation contract)."""
        self._change_seq += 1
        for _prio, _order, listener in self._generation_listeners:
            listener(self._change_seq)

    def add_generation_listener(
        self, fn: Callable[[int], None], priority: int = 0
    ) -> None:
        """Register a callback fired on every generation bump (LSDB
        change or RibPolicy set/clear).  Listeners fire in ascending
        ``(priority, registration order)`` — the order is STABLE, so
        cache-PURGING listeners (QueryService's result-cache
        invalidation, default priority 0) always run before listeners
        that MINT new state from the fresh generation (the streaming
        tier's publish scheduler registers at priority 10): a snapshot
        computed inside a later listener can never race a purge of its
        own generation's entries."""
        entry = (priority, len(self._generation_listeners), fn)
        self._generation_listeners.append(entry)
        self._generation_listeners.sort(key=lambda e: (e[0], e[1]))

    def pending_delta_hint(self) -> tuple:
        """``(full, changed_prefixes)`` — the delta class of the
        un-rebuilt LSDB window, read by generation listeners (the
        streaming tier) AT BUMP TIME to scope their own diffs.  ``full``
        is True when a topology/policy/static change is pending: such a
        tick can move routes for ANY prefix at ANY vantage.  When False,
        only the returned prefixes' advertisements changed, so no other
        prefix's computed route (at any vantage) can differ — the
        per-prefix delta discipline ``take_last_changed_prefixes``
        applies to the publication diff, extended to the watch plane.
        The returned set is live; callers must copy, not hold."""
        full = (
            self._pending_topo_changed
            or self._pending_force_full
            or not self._first_build_done
        )
        return full, self._pending_prefix_changes

    def rebuild_settled(self) -> bool:
        """True when the computed RIB reflects the current LSDB (first
        build done, no rebuild pending) — the protection tier only
        mints from a settled generation, so a patch's base RIB is
        exactly ``route_db``."""
        return self._first_build_done and not self._rebuild_pending

    # -- fast-reroute protection (apply + confirm authority) ----------------

    def _maybe_apply_protection(self, prev_key: tuple) -> None:
        """Classify the just-ingested publication; on a protected
        single-failure event with a generation-exact protection hit,
        publish the precomputed FIB patch IMMEDIATELY — failure
        convergence becomes a table lookup.  The debounced warm solve
        that follows is the confirming authority (``_confirm_frr``).
        Every refusal is counted ``protection.fallback.<reason>`` and
        degrades to the warm path, never to a wrong answer."""
        svc = self.protection
        pairs = self._pending_down_pairs
        if svc is None or not pairs:
            return
        if not self._first_build_done or not self._unblocked:
            return
        patch_key = svc.classify_pairs(pairs)
        if patch_key is None:
            svc.note_fallback("multi_failure")
            return
        if (
            self._pending_other_change
            or self._pending_force_full
            or self._pending_prefix_changes
            or self._frr_outstanding is not None
        ):
            # the un-rebuilt window carries MORE than this link-down
            # (or a prior patch is still unconfirmed): the patch's base
            # RIB assumption does not hold
            svc.note_fallback("stale")
            return
        status, doc = svc.lookup(prev_key, patch_key)
        if status != "hit":
            svc.note_fallback(status)
            return
        t0 = self.clock.now()
        made = svc.apply_patch(doc, self.prefix_state)
        if made is None:
            svc.note_fallback("miss")
            return
        entries, deletes = made
        from openr_tpu.tracing import pipeline as _pipeline
        from openr_tpu.tracing.pipeline import disabled_probe

        probe = self._backend_probe()
        if probe is None:
            probe = disabled_probe()
        span = self.tracer.start_span(
            "decision.frr_apply", self.pending_trace_ctx, module="decision"
        )
        try:
            with probe.phase(_pipeline.PROTECTION_APPLY):
                update = DecisionRouteUpdate(
                    type=DecisionRouteUpdateType.INCREMENTAL,
                    frr=True,
                    frr_generation=self._change_seq,
                )
                for prefix, entry in entries.items():
                    old = self.route_db.unicast_routes.get(prefix)
                    if old is None or not old.eq_ignoring_cost(entry):
                        update.unicast_routes_to_update[prefix] = entry
                update.unicast_routes_to_delete = [
                    p for p in deletes if p in self.route_db.unicast_routes
                ]
                # record what the FIB holds ON TOP of route_db until
                # the confirming warm solve reconciles it; route_db
                # itself is NOT mutated (warm backends patch from it)
                self._frr_outstanding = {
                    "generation": self._change_seq,
                    "entries": dict(update.unicast_routes_to_update),
                    "deletes": list(update.unicast_routes_to_delete),
                }
                if not update.empty():
                    # pending_trace_ctx is NOT consumed: the confirming
                    # rebuild parents its own span on the same event,
                    # and child_ctx preserves t0 so Fib's convergence
                    # histogram measures event -> patched, not apply
                    update.trace_ctx = self.tracer.child_ctx(
                        span, self.pending_trace_ctx
                    )
                    self.route_updates_queue.push(update)
        finally:
            self.tracer.end_span(span)
        apply_ms = (self.clock.now() - t0) * 1000.0
        self.counters.bump("decision.frr_applied")
        self.counters.observe("decision.frr_apply_ms", apply_ms)
        svc.note_applied(
            patch_key,
            len(self._frr_outstanding["entries"]),
            len(self._frr_outstanding["deletes"]),
            apply_ms,
        )

    def _confirm_frr(
        self, update: DecisionRouteUpdate, new_db: DecisionRouteDb
    ) -> DecisionRouteUpdate:
        """The confirm-authority step: the warm solve's ``new_db`` is
        the truth; the FIB currently holds ``route_db ⊕ patch``.  On a
        generation-exact divergence the patch LIED — purge the table,
        dump the flight recorder, and replace the whole RIB (no
        incremental delta from a lying table is trusted).  Otherwise
        reconcile the diff (computed against route_db alone) so the FIB
        lands exactly on ``new_db``; confirmed patch entries drop out
        of the push instead of being re-programmed."""
        frr, self._frr_outstanding = self._frr_outstanding, None
        svc = self.protection
        exact = frr["generation"] == self._change_seq
        mismatched = []
        for prefix, entry in frr["entries"].items():
            got = new_db.unicast_routes.get(prefix)
            if got is None or not got.eq_ignoring_cost(entry):
                mismatched.append(prefix)
        for prefix in frr["deletes"]:
            if prefix in new_db.unicast_routes:
                mismatched.append(prefix)
        if exact and mismatched:
            self.counters.bump("decision.frr_mismatches")
            if svc is not None:
                svc.on_mismatch(sorted(mismatched))
            return DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update=dict(new_db.unicast_routes),
                mpls_routes_to_update=dict(new_db.mpls_routes),
            )
        if svc is not None:
            svc.note_confirm(exact)
        deletes = set(update.unicast_routes_to_delete)
        for prefix, entry in frr["entries"].items():
            truth = new_db.unicast_routes.get(prefix)
            if truth is None:
                deletes.add(prefix)
            elif truth.eq_ignoring_cost(entry):
                update.unicast_routes_to_update.pop(prefix, None)
                deletes.discard(prefix)
            else:
                update.unicast_routes_to_update[prefix] = truth
                deletes.discard(prefix)
        for prefix in frr["deletes"]:
            if prefix in frr["entries"]:
                continue
            truth = new_db.unicast_routes.get(prefix)
            if truth is None:
                # the FIB already dropped it with the patch
                deletes.discard(prefix)
            else:
                update.unicast_routes_to_update[prefix] = truth
                deletes.discard(prefix)
        update.unicast_routes_to_delete = sorted(deletes)
        return update

    def generation_key(self) -> tuple:
        """Content address of the state every computed-result query
        depends on: the change generation (LSDB churn + policy flips)
        plus each area's topology sequence.  Two equal keys guarantee a
        cached answer is still exact; any LSDB or policy change produces
        a fresh key."""
        return (
            self._change_seq,
            tuple(
                (a, self.area_link_states[a].topology_seq)
                for a in sorted(self.area_link_states)
            ),
        )

    def _bulk_update_prefix_keys(self, area: str, items: List[tuple]) -> bool:
        """Native-kernel batch ingest of ``prefix:`` values (the cold-boot
        hot path; reference analogue: generated-C++ thrift decode feeding
        mergeKeyValues, KvStoreUtil.cpp:391).  Semantics are identical to
        per-key `_update_key`: rows the kernel can't express fall back to
        the scalar path, deletes use the key's prefix, updates use the
        payload's (canonical) prefix."""
        from openr_tpu.decision.ingest import ST_DELETE, ST_FAST, get_bulk_decoder

        dec = get_bulk_decoder()
        status, entries = dec.decode([payload for _, payload in items])
        changed_set = self._pending_prefix_changes
        changed = False
        update_changed = self.prefix_state.update_prefix_changed
        for i, (key, payload) in enumerate(items):
            parsed = parse_prefix_key(key)
            if parsed is None:
                continue  # not a prefix key after all (marker collision)
            st = status[i]
            if st == ST_FAST:
                entry = entries[i]
                origin_node = parsed[0]
                if update_changed(origin_node, area, entry):
                    changed_set.add(entry.prefix)
                    changed = True
            elif st == ST_DELETE:
                got = self.prefix_state.delete_prefix(
                    parsed[0], area, parsed[1]
                )
                if got:
                    changed_set |= got
                    changed = True
            else:
                changed |= self._update_key(area, key, payload)
        return changed

    def _update_key(self, area: str, key: str, data: bytes) -> bool:
        node = parse_adj_key(key)
        if node is not None:
            try:
                adj_db = deserialize_adj_db(data)
            except Exception:  # noqa: BLE001
                self.counters.bump("decision.parse_errors")
                return False
            if adj_db.perf_events is not None:
                self.pending_perf_events = adj_db.perf_events
                if adj_db.perf_events.trace_context is not None:
                    # payload-embedded context survives KvStore storage:
                    # prefer it so full-sync-delivered keys still join
                    # the originating event's trace
                    self.pending_trace_ctx = adj_db.perf_events.trace_context
            # structural classification BEFORE the update: a node's
            # first adjacency advertisement (or a fresh area) changes
            # the symbol table, and a link entering/leaving the LSDB
            # (a neighbor withdrawing its side of an adjacency when a
            # peer bounces — the rolling-restart delta class) changes
            # the edge-row membership.  Both route through the
            # slot-stable structural warm path; only pure
            # weight/drain/up-down flips stay perturbation-class.
            new_area = area not in self.area_link_states
            ls = self._get_link_state(area)
            new_node = not ls.has_node(node)
            links_before = ls.num_links()
            change = ls.update_adjacency_database(adj_db)
            if change.topology_changed or change.node_label_changed:
                self._pending_topo_changed = True
                if (
                    new_area
                    or new_node
                    or change.added_links
                    or ls.num_links() != links_before
                ):
                    self._pending_topo_structural = True
                if self.protection is not None:
                    for lk in change.down_links:
                        self._pending_down_pairs.add(
                            tuple(sorted((lk.n1, lk.n2)))
                        )
                    if (
                        change.other_topology_change
                        or change.node_label_changed
                    ):
                        self._pending_other_change = True
                return True
            return False
        parsed = parse_prefix_key(key)
        if parsed is not None:
            origin_node, prefix = parsed
            try:
                prefix_db = deserialize_prefix_db(data)
            except Exception:  # noqa: BLE001
                self.counters.bump("decision.parse_errors")
                return False
            if prefix_db.delete_prefix or not prefix_db.prefix_entries:
                changed_set = self.prefix_state.delete_prefix(
                    origin_node, area, prefix
                )
            else:
                changed_set = set()
                for entry in prefix_db.prefix_entries:
                    changed_set |= self.prefix_state.update_prefix(
                        origin_node, area, entry
                    )
            self._pending_prefix_changes |= changed_set
            return bool(changed_set)
        return False

    def _delete_key(self, area: str, key: str) -> bool:
        node = parse_adj_key(key)
        if node is not None:
            ls = self._get_link_state(area)
            if ls.delete_adjacency_database(node).topology_changed:
                self._pending_topo_changed = True
                # a node left the LSDB: the symbol table shrinks
                self._pending_topo_structural = True
                self._pending_other_change = True
                return True
            return False
        parsed = parse_prefix_key(key)
        if parsed is not None:
            origin_node, prefix = parsed
            changed_set = self.prefix_state.delete_prefix(
                origin_node, area, prefix
            )
            self._pending_prefix_changes |= changed_set
            return bool(changed_set)
        return False

    # -- static routes (PrefixManager originated w/ install_to_fib) --------

    def _on_static_routes(self, update: DecisionRouteUpdate) -> None:
        self.solver.update_static_unicast_routes(
            update.unicast_routes_to_update,
            update.unicast_routes_to_delete,
        )
        self._rebuild_pending = True
        self._pending_force_full = True
        if self._unblocked:
            self._debounce()

    # -- rebuild (rebuildRoutes, Decision.cpp:885) -------------------------

    def _rebuild_routes(self) -> None:
        if not self._unblocked:
            return
        large = self.prefix_state.get_received_routes_count() >= 10_000
        if large:
            # same GC discipline as bulk ingest: a reference-scale full
            # build allocates ~4 container objects per route and gen-2
            # collections re-scan the whole LSDB+RIB heap mid-build
            from openr_tpu.common.utils import gc_paused

            with gc_paused():
                self._rebuild_routes_inner()
            if self._first_build_done:
                self._end_boot_gc_window()
            return
        self._rebuild_routes_inner()
        if self._first_build_done and self._boot_gc_paused:
            self._end_boot_gc_window()

    def _end_boot_gc_window(self) -> None:
        """Boot steady state reached: the LSDB + first RouteDb are
        long-lived by design — collect once (purge any cycles created
        while the boot pause was active; the CPython-documented
        pre-freeze step), then move the surviving heap to the permanent
        generation so later full collections never re-scan it.  The C++
        reference pays zero cycle-collector tax on its LSDB; gc.freeze
        is CPython's mechanism for exactly that.  ONCE per process —
        the latch is module-global, not per-instance, so multi-node
        in-process deployments (EmulatedNetwork) don't repeatedly
        freeze each other's transient heaps."""
        import gc

        global _GC_FROZEN
        if self._boot_gc_paused:
            self._boot_gc_paused = False
            gc.enable()
        if not _GC_FROZEN:
            _GC_FROZEN = True
            gc.collect()
            gc.freeze()
            self.counters.set("decision.gc_freeze_rib", 1)

    def _rebuild_routes_inner(self) -> None:
        self._rebuild_pending = False
        t0 = self.clock.now()
        trace_ctx, self.pending_trace_ctx = self.pending_trace_ctx, None
        rebuild_span = self.tracer.start_span(
            "decision.rebuild", trace_ctx, module="decision"
        )
        try:
            self._rebuild_routes_traced(t0, trace_ctx, rebuild_span)
        finally:
            self.tracer.end_span(rebuild_span)

    def _rebuild_routes_traced(self, t0, trace_ctx, rebuild_span) -> None:
        policy_active = self.rib_policy is not None and self.rib_policy.is_active(
            self.clock
        )
        # incremental recompute gating (Decision.cpp:908-952): a pure
        # prefix-only delta lets the backend patch its previous RouteDb;
        # topology churn, static-route changes, policy application (which
        # mutates the returned db in place) and the first build force full
        force_full = (
            not self._first_build_done
            or self._pending_force_full
            or self._pending_topo_changed
            or policy_active
            or self._last_policy_active
        )
        # warm-rebuild hint (ISSUE 9): every pending topology change is a
        # perturbation (no node/area structural churn) and nothing ELSE
        # forced the full build — the backend may then rebuild its device
        # state incrementally from the previous generation, provided its
        # own caches corroborate (it re-verifies structural compatibility)
        warm_delta = (
            self._first_build_done
            and self._pending_topo_changed
            and not self._pending_topo_structural
            and not self._pending_force_full
            and not policy_active
            and not self._last_policy_active
        )
        # structural warm hint (ISSUE 12): node/area membership churn —
        # the delta class a rolling restart, autoscaling event or LSDB
        # key expiry produces.  The backend routes it through the
        # slot-stable encode patch + the generation-delta reset frontier
        # (tombstoned slots reset to +inf) instead of a cold re-encode;
        # its own caches still re-verify compatibility, and any decline
        # (slot exhaustion, area membership change) rebuilds cold with a
        # counted reason.
        structural_delta = (
            self._first_build_done
            and self._pending_topo_changed
            and self._pending_topo_structural
            and not self._pending_force_full
            and not policy_active
            and not self._last_policy_active
        )
        changed = self._pending_prefix_changes
        self._pending_prefix_changes = set()
        self._pending_topo_changed = False
        self._pending_topo_structural = False
        self._pending_force_full = False
        self._pending_down_pairs = set()
        self._pending_other_change = False
        self._last_policy_active = policy_active
        if not force_full and changed:
            self.counters.bump("decision.incremental_route_builds")
        if warm_delta:
            self.counters.bump("decision.warm_delta_builds")
        if structural_delta:
            self.counters.bump("decision.structural_delta_builds")
        # SPF dispatch span: the backend call (scalar solve or device
        # kernel pipeline); guarded jitted dispatches inside it record
        # `decision.spf_kernel` child spans via the jit_guard trace scope
        spf_span = self.tracer.start_span(
            "decision.spf",
            self.tracer.child_ctx(rebuild_span, trace_ctx),
            module="decision",
            backend=type(self.backend).__name__,
            force_full=force_full,
        )
        from openr_tpu.ops import jit_guard

        try:
            with jit_guard.trace_scope(
                self.tracer, self.tracer.child_ctx(spf_span, trace_ctx)
            ):
                new_db = self.backend.build_route_db(
                    self.area_link_states,
                    self.prefix_state,
                    changed_prefixes=(
                        changed if self._first_build_done else None
                    ),
                    force_full=force_full,
                    cache_result=not policy_active,
                    warm_delta=warm_delta,
                    structural_delta=structural_delta,
                )
        finally:
            self.tracer.end_span(spf_span)
            spf_ms = spf_span.duration_ms()
            if spf_ms is not None:
                self.counters.observe("decision.spf_ms", spf_ms)
        self.counters.bump("decision.route_build_runs")
        if new_db is None:
            return
        if self.rib_policy is not None and self.rib_policy.is_active(self.clock):
            self.rib_policy.apply_policy(new_db, self.clock)
        if self.backend.take_full_replace():
            # quarantine swap: the backend replaced corrupt device output
            # with the scalar oracle's FULL db — diff everything so
            # corrupt entries from unsampled builds are purged from the
            # FIB, not just this tick's changed prefixes
            self.counters.bump("decision.quarantine_full_replaces")
            force_full = True
            if self.protection is not None:
                # purge-on-suspicion: the device path just produced
                # corrupt output — nothing it minted is trusted either
                self.protection.purge_table("full_replace")
        # the RouteDb diff is the pipeline's delta-extract tail: the last
        # host stage between device output and the FIB publication
        probe = self._backend_probe()
        if probe is None:
            from openr_tpu.tracing.pipeline import disabled_probe

            probe = disabled_probe()
        from openr_tpu.tracing import pipeline as _pipeline

        with probe.phase(_pipeline.DELTA_EXTRACT):
            warm_changed = None
            if force_full:
                # a warm-selective backend build PATCHED the previous
                # RouteDb and reports exactly which prefixes could have
                # moved — every other entry is object-identical, so the
                # diff stays O(perturbation) even on a topology tick
                take = getattr(
                    self.backend, "take_last_changed_prefixes", None
                )
                if take is not None:
                    warm_changed = take()
            if force_full and warm_changed is not None:
                self.counters.bump("decision.warm_selective_diffs")
                update = self.route_db.calculate_update_for(
                    new_db, warm_changed
                )
            elif force_full:
                update = self.route_db.calculate_update(new_db)
            else:
                # incremental contract: only the changed prefixes can
                # differ — diff O(changed) instead of O(total) so the
                # publication→FIB latency stays flat in prefix count
                update = self.route_db.calculate_update_for(new_db, changed)
        if self._frr_outstanding is not None:
            update = self._confirm_frr(update, new_db)
        first = not self._first_build_done
        if first:
            update = DecisionRouteUpdate(
                type=DecisionRouteUpdateType.FULL_SYNC,
                unicast_routes_to_update=dict(new_db.unicast_routes),
                mpls_routes_to_update=dict(new_db.mpls_routes),
            )
        self.route_db = new_db
        self.counters.set(
            "decision.route_build_ms", (self.clock.now() - t0) * 1000.0
        )
        self.counters.set(
            "decision.num_routes", len(new_db.unicast_routes)
        )
        if first or not update.empty():
            pe = self.pending_perf_events or PerfEvents()
            pe.add(self.node_name, "DECISION_ROUTE_BUILD", self.clock.now_ms())
            update.perf_events = pe
            self.pending_perf_events = None
            # Fib's programming span parents under this rebuild
            update.trace_ctx = self.tracer.child_ctx(rebuild_span, trace_ctx)
            self.route_updates_queue.push(update)
        if first:
            self._first_build_done = True
            if self.initialization_cb is not None:
                self.initialization_cb(InitializationEvent.RIB_COMPUTED)

    # -- RibPolicy API (setRibPolicy, Decision.cpp:634) --------------------

    def set_rib_policy(self, policy: RibPolicy) -> None:
        self.rib_policy = policy
        self._save_rib_policy()
        # a policy flip changes what every computed-result query would
        # return even on an identical LSDB: the fleet/what-if table
        # caches and the serving result cache key on this generation.
        # force_full is set BEFORE the bump so pending_delta_hint reads
        # "full" inside the listeners this bump fires
        self._pending_force_full = True
        self._bump_generation()
        self._rebuild_pending = True
        if self._unblocked:
            self._debounce()

    def get_rib_policy(self) -> Optional[RibPolicy]:
        return self.rib_policy

    def clear_rib_policy(self) -> None:
        self.rib_policy = None
        if self.rib_policy_file and os.path.exists(self.rib_policy_file):
            os.unlink(self.rib_policy_file)
        self._pending_force_full = True
        self._bump_generation()
        self._rebuild_pending = True
        if self._unblocked:
            self._debounce()

    def _save_rib_policy(self) -> None:
        if not self.rib_policy_file or self.rib_policy is None:
            return
        with open(self.rib_policy_file, "w") as f:
            f.write(self.rib_policy.to_json(self.clock))

    def _load_rib_policy(self) -> None:
        if not self.rib_policy_file or not os.path.exists(self.rib_policy_file):
            return
        try:
            with open(self.rib_policy_file) as f:
                self.rib_policy = RibPolicy.from_json(f.read(), self.clock)
        except (ValueError, KeyError):
            self.counters.bump("decision.rib_policy_load_errors")

    # -- ctrl surface ------------------------------------------------------

    def get_route_db(self) -> DecisionRouteDb:
        return self.route_db

    def get_adj_dbs(self, area: Optional[str] = None) -> List[AdjacencyDatabase]:
        out = []
        for a, ls in self.area_link_states.items():
            if area is not None and a != area:
                continue
            out.extend(ls.get_adjacency_databases().values())
        return out

    def get_received_routes(self) -> Dict[str, dict]:
        return {
            prefix: {f"{n}@{a}": e.to_wire() for (n, a), e in entries.items()}
            for prefix, entries in self.prefix_state.prefixes().items()
        }

    def _backend_pool(self):
        """The backend's DevicePool when multi-chip dispatch is active
        — the fleet/what-if engines then spread their batches over the
        same health-governed chips route builds use (a quarantined
        chip serves no computed-result queries either)."""
        fn = getattr(self.backend, "dispatch_pool", None)
        return fn() if fn is not None else None

    def _backend_probe(self):
        """The backend's PipelineProbe (None for scalar backends) — the
        fleet/what-if engines record their phase samples and per-chip
        busy time on the SAME ledger route builds use, so `pipeline.*`
        histograms and `pipeline.devN.*` gauges cover the whole
        dispatch plane."""
        return getattr(self.backend, "probe", None)

    def _fleet(self):
        if self._fleet_engine is None:
            from openr_tpu.decision.fleet import FleetRibEngine

            self._fleet_engine = FleetRibEngine(
                self.solver,
                pool=self._backend_pool(),
                probe=self._backend_probe(),
            )
        return self._fleet_engine

    def device_available(self) -> bool:
        """Device compute usable for fleet/what-if answers: a device
        backend whose accelerator is not in an (injected or real)
        outage.  While `device_failed` is set — chaos `tpu_fail`, or an
        operator draining a sick accelerator — every computed-result
        query must degrade to the scalar/native paths exactly like the
        daemon's own route builds do."""
        return not isinstance(self.backend, ScalarBackend) and not getattr(
            self.backend, "device_failed", False
        )

    def capacity_sweep_inputs(self) -> dict:
        """Everything the capacity-sweep executor (openr_tpu.sweep)
        reads per context build, as one public surface: the live LSDB +
        prefix state + change generation, the backend's DevicePool /
        PipelineProbe / health governor (the sweep dispatches over the
        same health-governed chips route builds use), and the
        selection-rule flag its multi-area decode needs.  The kwargs of
        :class:`openr_tpu.sweep.executor.SweepInputs`."""
        from openr_tpu.types import RouteComputationRules

        return {
            "area_link_states": self.area_link_states,
            "prefix_state": self.prefix_state,
            "change_seq": self._change_seq,
            "root": self.solver.my_node_name,
            "pool": self._backend_pool(),
            "probe": self._backend_probe(),
            "governor": getattr(self.backend, "governor", None),
            "per_area_distance": (
                self.solver.route_selection_algorithm
                == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
            ),
        }

    def compute_route_db_for_node(self, node: str) -> Optional[DecisionRouteDb]:
        """What-if: the RouteDb as `node` would compute it
        (getRouteDbComputed ctrl API).  When the device fleet engine is
        eligible, ALL nodes' tables come from one cached batch solve and
        only this node's view is decoded; else a fresh scalar pass."""
        if self.device_available():
            fleet = self._fleet()
            if fleet.eligible(
                self.area_link_states, self.prefix_state, self._change_seq
            ):
                try:
                    db = fleet.compute_for_node(
                        node,
                        self.area_link_states,
                        self.prefix_state,
                        self._change_seq,
                    )
                except ValueError:  # candidate-bucket overflow → scalar
                    db = None
                if db is not None:
                    return db
        solver = SpfSolver(
            node,
            enable_v4=self.solver.enable_v4,
            enable_node_segment_label=self.solver.enable_node_segment_label,
            enable_best_route_selection=self.solver.enable_best_route_selection,
            v4_over_v6_nexthop=self.solver.v4_over_v6_nexthop,
            route_selection_algorithm=self.solver.route_selection_algorithm,
        )
        return solver.build_route_db(self.area_link_states, self.prefix_state)

    def get_link_criticality(self, max_pairs: int = 0) -> Optional[dict]:
        """Blast-radius report: ONE device sweep failing EVERY link
        ranks links by withdrawn/changed routes; ``max_pairs`` > 0 adds
        an exhaustive double-failure scan (run_sets over on-DAG pairs,
        capped) flagging pairs whose combined failure withdraws routes
        neither single failure does — partition risk.  Net-new vs the
        reference (its tooling answers one failure at a time); the
        batch shape is exactly what the set-repair kernel exists for.
        None = ineligible (device feature: scalar-only deployments and
        multi-area vantages decline; KSP2 declines via fleet gating)."""
        if not self.device_available():
            return None
        if len(self.area_link_states) != 1:
            return None
        if not self._fleet().eligible(
            self.area_link_states, self.prefix_state, self._change_seq
        ):
            return None
        if self._whatif_engine is None:
            from openr_tpu.decision.whatif_api import WhatIfApiEngine

            self._whatif_engine = WhatIfApiEngine(self.solver)
        from openr_tpu.decision.whatif_api import (
            _whatif_engine_criticality,
        )

        try:
            result = _whatif_engine_criticality(
                self._whatif_engine,
                self.area_link_states,
                self.prefix_state,
                self._change_seq,
                max_pairs=max_pairs,
            )
        except ValueError:
            return None
        self.counters.bump("decision.criticality_reports")
        return result

    def _generic_whatif(self):
        """Lazy algorithm-complete fallback engine (jax-free)."""
        if self._whatif_generic_engine is None:
            from openr_tpu.decision.whatif_api import (
                GenericSolverWhatIfEngine,
            )

            self._whatif_generic_engine = GenericSolverWhatIfEngine(
                self.solver
            )
        return self._whatif_generic_engine

    def get_link_failure_whatif(
        self, link_failures: List, simultaneous: bool = False
    ) -> Optional[dict]:
        """'Which of MY routes change if these links fail?' — one
        warm-start sweep over the candidate failures (the flagship
        what-if machinery, cached per LSDB generation).  With
        ``simultaneous``, ALL listed links fail AT ONCE (maintenance-
        window analysis).  Engine choice: single-area vantages pick
        native-vs-device by measured dispatch RT; multi-area LSDBs run
        the set-capable multi-area kernel (singles, bundles AND
        simultaneous sets); KSP2/exotic-algorithm vantages run
        device-backed full builds (DeviceBuildWhatIfEngine).  Only
        scalar-only deployments beyond the native engine's reach fall
        back to the jax-free GenericSolverWhatIfEngine.  None only when
        there is no LSDB yet or a build overflows the candidate
        buckets."""
        scalar_only = not self.device_available()
        fleet = self._fleet()
        if not self.area_link_states:
            return None
        fleet_ok = fleet.eligible(
            self.area_link_states, self.prefix_state, self._change_seq
        )
        generic_reasons = (
            # KSP2 / unsupported selection algorithm on a SCALAR-ONLY
            # deployment: only the jax-free full solver may serve it
            (not fleet_ok and scalar_only)
            # the multi-area engines are device-only; a scalar
            # deployment must never pull in the device stack
            or (scalar_only and len(self.area_link_states) != 1)
        )
        if generic_reasons:
            # algorithm-complete fallback: rebuild the LSDB minus the
            # links and run the FULL solver (jax-free; slow but exact
            # for every configuration the daemon can run)
            result = self._generic_whatif().run(
                [tuple(f) for f in link_failures],
                self.area_link_states,
                self.prefix_state,
                self._change_seq,
                simultaneous=simultaneous,
            )
            if result is not None:
                self.counters.bump("decision.whatif.engine.generic")
            return result
        if not fleet_ok:
            # KSP2 prefixes / exotic selection with a device backend:
            # full builds minus the links on the DEVICE compute path
            # (tables + device KSP2) — the same engines the daemon's
            # own route builds use for these algorithms
            if self._whatif_device_build_engine is None:
                from openr_tpu.decision.whatif_api import (
                    DeviceBuildWhatIfEngine,
                )

                self._whatif_device_build_engine = DeviceBuildWhatIfEngine(
                    self.solver
                )
            result = self._whatif_device_build_engine.run(
                [tuple(f) for f in link_failures],
                self.area_link_states,
                self.prefix_state,
                self._change_seq,
                simultaneous=simultaneous,
            )
            if result is not None:
                self.counters.bump("decision.whatif.engine.device_build")
            return result
        if len(self.area_link_states) == 1:
            # single-area vantage: pick the warm-start engine by where
            # it runs cheapest — the native C++ sweep solves a handful
            # of failures in microseconds, while the device path pays
            # dispatch round trips it can only amortize over large
            # batches (the same measured-RT calibration the backend's
            # device cutover uses)
            use_native = self._use_native_whatif(
                1 if simultaneous else len(link_failures)
            )
            if scalar_only and not use_native:
                # the device engine would load jax (forbidden on a
                # scalar-only deployment) and the native engine declined
                # (vantage fan-out beyond its lane limit, or a batch the
                # calibration priced for the device): answer through the
                # jax-free generic solver instead of going ineligible
                result = self._generic_whatif().run(
                    [tuple(f) for f in link_failures],
                    self.area_link_states,
                    self.prefix_state,
                    self._change_seq,
                    simultaneous=simultaneous,
                )
                if result is not None:
                    self.counters.bump(
                        "decision.whatif.engine.generic"
                    )
                return result
            if use_native:
                if self._whatif_native_engine is None:
                    from openr_tpu.decision.whatif_api import (
                        NativeWhatIfEngine,
                    )

                    self._whatif_native_engine = NativeWhatIfEngine(
                        self.solver
                    )
                engine = self._whatif_native_engine
                engine_name = "native"
            else:
                if self._whatif_engine is None:
                    from openr_tpu.decision.whatif_api import (
                        WhatIfApiEngine,
                    )

                    self._whatif_engine = WhatIfApiEngine(self.solver)
                engine = self._whatif_engine
                engine_name = "device"
        else:
            # multi-area LSDB: fleet-family kernel (per-snapshot masked
            # area re-solve + global selection + cross-area merge)
            if self._whatif_multi_engine is None:
                from openr_tpu.decision.whatif_api import (
                    MultiAreaWhatIfEngine,
                )

                self._whatif_multi_engine = MultiAreaWhatIfEngine(
                    self.solver,
                    pool=self._backend_pool(),
                    probe=self._backend_probe(),
                )
            engine = self._whatif_multi_engine
            engine_name = "multiarea"
        try:
            kwargs = {"simultaneous": True} if simultaneous else {}
            result = engine.run(
                [tuple(f) for f in link_failures],
                self.area_link_states,
                self.prefix_state,
                self._change_seq,
                **kwargs,
            )
            # counted only once an answer actually came back
            self.counters.bump(f"decision.whatif.engine.{engine_name}")
            return result
        except ValueError:
            # e.g. an anycast prefix wider than the largest candidate
            # bucket.  Multi-area queries previously ANSWERED such
            # configurations through the generic scalar engine — keep
            # that: a device-table overflow must not downgrade a
            # formerly-answerable query to ineligible (r5 review).
            if engine_name == "multiarea":
                result = self._generic_whatif().run(
                    [tuple(f) for f in link_failures],
                    self.area_link_states,
                    self.prefix_state,
                    self._change_seq,
                    simultaneous=simultaneous,
                )
                if result is not None:
                    self.counters.bump("decision.whatif.engine.generic")
                return result
            return None

    def get_decision_paths(
        self, src: str, dst: str, max_hop: int = 256,
        area: Optional[str] = None,
    ) -> dict:
        """Enumerate loop-free src→dst forwarding paths by walking each
        hop's COMPUTED RouteDb (the reference's `breeze decision path`
        DFS over getRouteDbComputed, decision.py:309-360 of its CLI) —
        here each hop's routes decode from the fleet engine's one batch
        solve instead of a scalar Dijkstra per hop.

        ``dst`` is a prefix or a node name (resolved to that node's
        first advertised prefix, the loopback convention).  ``area``
        restricts hop expansion to nexthops learned in that area (the
        reference CLI's --area)."""
        prefixes = self.prefix_state.prefixes()
        if dst in prefixes:
            dst_prefix = dst
        else:
            advertised = sorted(
                p
                for p, entries in prefixes.items()
                if any(node == dst for (node, _a) in entries)
            )
            if not advertised:
                return {
                    "src": src,
                    "dst": dst,
                    "error": f"{dst!r} is neither a known prefix nor an "
                    "advertising node",
                    "paths": [],
                }
            dst_prefix = advertised[0]
        advertisers = {node for (node, _a) in prefixes[dst_prefix]}

        route_cache: Dict[str, object] = {}

        def route_entry(node):
            if node not in route_cache:
                db = self.compute_route_db_for_node(node)
                route_cache[node] = (
                    None
                    if db is None
                    else db.unicast_routes.get(dst_prefix)
                )
            return route_cache[node]

        paths: List[dict] = []
        truncated = [False]

        def dfs(cur, path, visited):
            if len(paths) >= 1024:
                truncated[0] = True
                return
            if cur in advertisers:
                paths.append(list(path))
                return
            if len(path) - 1 >= max_hop:
                truncated[0] = True
                return
            entry = route_entry(cur)
            if entry is None:
                return  # dead end: cur computes no route for dst
            for nh in sorted(
                {
                    n.neighbor_node_name
                    for n in entry.nexthops
                    if area is None or n.area == area
                }
            ):
                if nh in visited:
                    continue
                visited.add(nh)
                path.append(nh)
                dfs(nh, path, visited)
                path.pop()
                visited.discard(nh)

        src_entry = route_entry(src) if src not in advertisers else None
        dfs(src, [src], {src})
        # metric: the src's computed route cost; 0 when src itself
        # advertises dst; None (not a fake zero) when src has no route
        if src in advertisers:
            metric = 0.0
        elif src_entry is not None:
            metric = float(src_entry.igp_cost)
        else:
            metric = None
        return {
            "src": src,
            "dst": dst,
            "dst_prefix": dst_prefix,
            "metric": metric,
            "truncated": truncated[0],
            "paths": [{"hops": p, "num_hops": len(p) - 1} for p in paths],
        }

    #: per-item cost of a native warm solve + numpy selection (rough;
    #: only needs to pick the right side of a ~100x crossover)
    NATIVE_US_PER_ITEM = 0.2

    def _use_native_whatif(self, num_failures: int) -> bool:
        """Native engine iff its estimated sweep cost undercuts the
        device path's dispatch round trips for this query size.  On a
        scalar-only deployment the native engine is the ONLY eligible
        one (no jax ever loads), so no probe runs."""
        from openr_tpu.decision.backend import (
            TpuBackend,
            estimate_scalar_work_items,
            measure_dispatch_rt_ms,
        )
        from openr_tpu.ops.native_spf import MAX_LANES

        me = self.node_name
        (ls,) = self.area_link_states.values()
        # the native solver packs first-hop lanes into one u64 word; a
        # vantage with more out-links than that stays on the device
        # engine (which handles up to the largest degree bucket)
        if len(ls.links_from_node(me)) > MAX_LANES:
            return False
        if not self.device_available():
            # scalar-only deployment, or the device is out: the native
            # engine is the only warm-start option left (no jax loads)
            return True
        is_tpu = isinstance(self.backend, TpuBackend)
        rt_ms = self.backend.auto_dispatch_rt_ms if is_tpu else None
        if rt_ms is None:
            rt_ms = self._whatif_rt_ms or measure_dispatch_rt_ms()
            self._whatif_rt_ms = rt_ms
            if is_tpu:
                # share the calibration so the backend's own cutover
                # doesn't measure again
                self.backend.auto_dispatch_rt_ms = rt_ms
        items = estimate_scalar_work_items(
            self.area_link_states, self.prefix_state
        )
        native_us = max(num_failures, 1) * items * self.NATIVE_US_PER_ITEM
        device_us = TpuBackend.DEVICE_OVERHEAD_TRIPS * rt_ms * 1000.0
        return native_us < device_us

    def get_fleet_rib_summary(self) -> Optional[Dict[str, dict]]:
        """Per-node route counts for EVERY vantage point from one batched
        device solve; None when the fleet engine isn't eligible (incl.
        scalar-only deployments, which must never touch the device
        stack, and device backends in an injected/real outage)."""
        if not self.device_available():
            return None
        fleet = self._fleet()
        if not fleet.eligible(
            self.area_link_states, self.prefix_state, self._change_seq
        ):
            return None
        try:
            return fleet.fleet_summary(
                self.area_link_states, self.prefix_state, self._change_seq
            )
        except ValueError:  # candidate-bucket overflow → ineligible
            return None
