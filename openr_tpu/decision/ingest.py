"""Bulk LSDB prefix ingest: native batch decode -> PrefixState.

Cold boot of a reference-scale LSDB (4096 nodes x 100 prefixes =
409,600 advertisements) was bounded by per-advertisement pure-Python
decode (~20 us each: json.loads + generic dataclass from_wire).  The
reference never pays that — its flood ingest is generated-C++ thrift
decode straight into structs (openr/kvstore/KvStoreUtil.cpp:391).  This
module is the equivalent native path: `native/lsdb_decode.cc` parses a
whole batch of payloads (wire-JSON or thrift-compact, sniffed per row)
into flat columns, and the Python side builds `PrefixEntry` objects via
``__new__`` + direct field stores — no json module, no generic
from_wire, no re-normalization (the kernel emits canonical prefixes).

Rows off the canonical shape (multi-entry, tags, area_stack,
perf_events, exotic addresses) are flagged and re-decoded by the scalar
path, so the kernel can never change semantics — only speed.  Decoded
parity between both paths is pinned in tests/test_ingest.py.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from openr_tpu.types import (
    PrefixEntry,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    PrefixMetrics,
    PrefixType,
)

LOG = logging.getLogger(__name__)

ST_FAST = 0
ST_FALLBACK = 1
ST_DELETE = 2

_PREFIX_CHARS = 64

#: enum interning tables: EnumType(value) costs ~0.3us per call; a dict
#: hit is ~10x cheaper and returns the identical singleton
_PT = {m.value: m for m in PrefixType}
_FT = {m.value: m for m in PrefixForwardingType}
_FA = {m.value: m for m in PrefixForwardingAlgorithm}


class _Cols(ctypes.Structure):
    _fields_ = [
        ("status", ctypes.c_void_p),
        ("prefix", ctypes.c_void_p),
        ("ptype", ctypes.c_void_p),
        ("fwd_type", ctypes.c_void_p),
        ("fwd_alg", ctypes.c_void_p),
        ("m_version", ctypes.c_void_p),
        ("m_path_pref", ctypes.c_void_p),
        ("m_source_pref", ctypes.c_void_p),
        ("m_distance", ctypes.c_void_p),
        ("m_drain", ctypes.c_void_p),
        ("min_nexthop", ctypes.c_void_p),
        ("weight", ctypes.c_void_p),
    ]


class BulkPrefixDecoder:
    """ctypes wrapper over lsdb_decode_prefix_batch."""

    def __init__(self) -> None:
        from openr_tpu.common.native import load_native_lib

        self._lib = load_native_lib("lsdb_decode")
        fn = self._lib.lsdb_decode_prefix_batch
        fn.restype = ctypes.c_int32
        fn.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            _Cols,
        ]
        self._fn = fn
        self._cap = 0
        self._bufs: Optional[tuple] = None
        self._cols: Optional[_Cols] = None

    def _ensure_capacity(self, n: int) -> None:
        """Output buffers are reused across batches (real floods arrive
        as many ~100-key publications; fresh numpy allocs + ctypes setup
        per batch would dominate small batches)."""
        if n <= self._cap:
            return
        cap = max(256, 1 << (n - 1).bit_length())
        offs = np.zeros(cap + 1, dtype=np.int64)
        status = np.empty(cap, dtype=np.uint8)
        prefix = np.zeros(cap, dtype=f"S{_PREFIX_CHARS}")
        i32 = lambda: np.empty(cap, dtype=np.int32)  # noqa: E731
        i64 = lambda: np.empty(cap, dtype=np.int64)  # noqa: E731
        arrs = (
            status, prefix, i32(), i32(), i32(),
            i32(), i32(), i32(), i32(), i32(),
            i64(), i64(),
        )

        def vp(a):
            return a.ctypes.data_as(ctypes.c_void_p)

        self._cols = _Cols(*[vp(a) for a in arrs])
        self._offs = offs
        self._offs_ptr = offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        self._bufs = arrs
        self._cap = cap

    def decode(self, payloads: Sequence[bytes]):
        """-> (status: List[int], entries: List[Optional[PrefixEntry]]).

        entries[i] is a PrefixEntry for ST_FAST rows, None otherwise."""
        n = len(payloads)
        self._ensure_capacity(n)
        buf = b"".join(payloads)
        offs = self._offs
        np.cumsum([len(p) for p in payloads], out=offs[1 : n + 1])
        (
            status, prefix, ptype, fwd_type, fwd_alg,
            m_version, m_path, m_src, m_dist, m_drain,
            min_nexthop, weight,
        ) = self._bufs
        # zero the prefix slots in use: the kernel NUL-terminates but
        # does not pad, and the S-dtype only strips TRAILING NULs
        prefix[:n] = b""
        self._fn(buf, self._offs_ptr, n, self._cols)

        # bulk-convert to python objects once (per-element numpy scalar
        # access would dominate the loop below)
        st = status[:n].tolist()
        pfx = prefix[:n].tolist()  # bytes, NUL-stripped by S-dtype
        t_l, ft_l, fa_l = (
            ptype[:n].tolist(), fwd_type[:n].tolist(), fwd_alg[:n].tolist()
        )
        mv_l, mp_l, ms_l = (
            m_version[:n].tolist(), m_path[:n].tolist(), m_src[:n].tolist()
        )
        md_l, mdr_l = m_dist[:n].tolist(), m_drain[:n].tolist()
        mnh_l, w_l = min_nexthop[:n].tolist(), weight[:n].tolist()

        INT64_MIN = -(2**63)
        e_new = PrefixEntry.__new__
        m_new = PrefixMetrics.__new__
        entries: List[Optional[PrefixEntry]] = [None] * n
        for i in range(n):
            if st[i] != ST_FAST:
                continue
            ptype = _PT.get(t_l[i])
            ftype = _FT.get(ft_l[i])
            falg = _FA.get(fa_l[i])
            if ptype is None or ftype is None or falg is None:
                # unknown enum value: the scalar path REJECTS the row
                # (EnumType(v) raises in from_wire -> parse_errors), so
                # the kernel must not quietly accept it as a bare int —
                # semantics live in one place
                st[i] = ST_FALLBACK
                continue
            m = m_new(PrefixMetrics)
            dm = m.__dict__
            dm["version"] = mv_l[i]
            dm["drain_metric"] = mdr_l[i]
            dm["path_preference"] = mp_l[i]
            dm["source_preference"] = ms_l[i]
            dm["distance"] = md_l[i]
            e = e_new(PrefixEntry)
            de = e.__dict__
            de["prefix"] = pfx[i].decode()
            de["type"] = ptype
            de["forwarding_type"] = ftype
            de["forwarding_algorithm"] = falg
            de["min_nexthop"] = None if mnh_l[i] < 0 else mnh_l[i]
            de["metrics"] = m
            de["tags"] = set()
            de["area_stack"] = []
            de["weight"] = None if w_l[i] == INT64_MIN else w_l[i]
            entries[i] = e
        return st, entries


_DECODER: Optional[BulkPrefixDecoder] = None
_DECODER_FAILED = False


def get_bulk_decoder() -> Optional[BulkPrefixDecoder]:
    """Process-wide decoder; None when the native lib can't build (the
    scalar path then serves everything)."""
    global _DECODER, _DECODER_FAILED
    if _DECODER is None and not _DECODER_FAILED:
        try:
            _DECODER = BulkPrefixDecoder()
        except Exception as e:  # noqa: BLE001 — no compiler, bad arch, ...
            _DECODER_FAILED = True
            LOG.warning("native lsdb decoder unavailable (%s); scalar path", e)
    return _DECODER
