"""SpfSolver: turn SPF results + prefix advertisements into a RouteDb.

Scalar reference implementation of openr/decision/SpfSolver.{h,cpp} and the
best-route selection helpers from openr/common/LsdbUtil.cpp:640-830.  The
batched device path in ``openr_tpu.ops`` implements the same selection
semantics; this module is the oracle and host fallback.

Semantics preserved:
  * candidate filtering by per-area reachability (SpfSolver.cpp:195-215)
  * hard-drain candidate filter w/ all-drained fallback (SpfSolver.cpp:527-545)
  * soft-drain detection feeding the drain tie-breaker (SpfSolver.cpp:512-525)
  * best-route metric chain: drained ▸ path_preference ▸ source_preference,
    then SHORTEST_DISTANCE / PER_AREA_SHORTEST_DISTANCE on metrics.distance
    (LsdbUtil.cpp:761-823)
  * skip-if-self: no route programmed for prefixes the local node advertises
    (SpfSolver.cpp:253-260)
  * ECMP nexthop computation: min-cost dest set, per-neighbor distance
    check distOverLink == minMetric (getNextHopsWithMetric/getNextHopsThrift,
    SpfSolver.cpp:649-768)
  * cross-area min-metric nexthop merge (SpfSolver.cpp:276-302)
  * min-nexthop threshold (addBestPaths, SpfSolver.cpp:596-620)
  * node-segment-label MPLS routes w/ PHP/SWAP/POP_AND_LOOKUP
    (buildRouteDb, SpfSolver.cpp:354-445)
  * static-route overlay (SpfSolver.cpp:109-137, 343-349)
  * KSP2_ED_ECMP restored as a first-class algorithm (the snapshot removed
    the solver path but kept the IDL + LinkState::getKthPaths; see stale
    comment SpfSolver.h:215): routes over the union of 1st and 2nd
    edge-disjoint shortest paths, with SR-MPLS label stacks pinning the
    non-shortest path when forwarding type is SR_MPLS.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.decision.link_state import INF, LinkState, Path
from openr_tpu.decision.prefix_state import NodeAndArea, PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, RibMplsEntry, RibUnicastEntry
from openr_tpu.types import (
    MplsAction,
    MplsActionCode,
    NextHop,
    PrefixEntry,
    prefix_is_v4,
    PrefixForwardingAlgorithm,
    PrefixForwardingType,
    RouteComputationRules,
)
from openr_tpu import constants as C

PrefixEntries = Dict[NodeAndArea, PrefixEntry]


def is_mpls_label_valid(label: int) -> bool:
    return C.MPLS_MIN_LABEL <= label <= C.MPLS_MAX_LABEL


def drained_entry(entry: PrefixEntry) -> PrefixEntry:
    """best-entry copy with drain_metric=1 so other areas learn this path
    crosses a drained node (addBestPaths, SpfSolver.cpp:628-636); shares
    every unchanged field — PrefixState never mutates entries in place,
    so the shared references are safe and no deepcopy is needed."""
    import dataclasses

    return dataclasses.replace(
        entry,
        metrics=type(entry.metrics)(
            version=entry.metrics.version,
            drain_metric=1,
            path_preference=entry.metrics.path_preference,
            source_preference=entry.metrics.source_preference,
            distance=entry.metrics.distance,
        ),
    )


@dataclass
class RouteSelectionResult:
    """Winner set of best-route selection (SpfSolver.h RouteSelectionResult)."""

    all_node_areas: Set[NodeAndArea] = field(default_factory=set)
    best_node_area: NodeAndArea = ("", "")
    is_best_node_drained: bool = False

    def has_node(self, node: str) -> bool:
        return any(n == node for n, _ in self.all_node_areas)


def select_routes(
    prefix_entries: PrefixEntries,
    algorithm: RouteComputationRules,
    drained_nodes: Set[NodeAndArea],
) -> Set[NodeAndArea]:
    """Best-route selection metric chain (LsdbUtil.cpp:761-823)."""
    best_tuple = (-(2**31), -(2**31), -(2**31))
    node_area_set: Set[NodeAndArea] = set()
    for key, entry in prefix_entries.items():
        m = entry.metrics
        t = (
            -int(bool(m.drain_metric or (key in drained_nodes))),
            m.path_preference,
            m.source_preference,
        )
        if t < best_tuple:
            continue
        if t > best_tuple:
            best_tuple = t
            node_area_set.clear()
        node_area_set.add(key)

    if algorithm == RouteComputationRules.SHORTEST_DISTANCE:
        return _select_shortest_distance(prefix_entries, node_area_set)
    if algorithm == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE:
        by_area: Dict[str, Set[NodeAndArea]] = {}
        for na in node_area_set:
            by_area.setdefault(na[1], set()).add(na)
        out: Set[NodeAndArea] = set()
        for in_area in by_area.values():
            out |= _select_shortest_distance(prefix_entries, in_area)
        return out
    return set()


def _select_shortest_distance(
    prefix_entries: PrefixEntries, node_area_set: Set[NodeAndArea]
) -> Set[NodeAndArea]:
    shortest = 2**31
    ret: Set[NodeAndArea] = set()
    for na in node_area_set:
        if na not in prefix_entries:
            continue
        dist = prefix_entries[na].metrics.distance
        if dist > shortest:
            continue
        if dist < shortest:
            shortest = dist
            ret.clear()
        ret.add(na)
    return ret


def select_best_node_area(
    all_node_areas: Set[NodeAndArea], my_node_name: str
) -> NodeAndArea:
    """Deterministic pick; prefer self (LsdbUtil.cpp:701-712)."""
    best = min(all_node_areas)
    for na in all_node_areas:
        if na[0] == my_node_name:
            return na
    return best


class SpfSolver:
    """Scalar route computation engine (openr/decision/SpfSolver.h:100-260)."""

    def __init__(
        self,
        my_node_name: str,
        enable_v4: bool = True,
        enable_node_segment_label: bool = False,
        enable_best_route_selection: bool = True,
        v4_over_v6_nexthop: bool = False,
        route_selection_algorithm: RouteComputationRules = (
            RouteComputationRules.SHORTEST_DISTANCE
        ),
    ) -> None:
        self.my_node_name = my_node_name
        self.enable_v4 = enable_v4
        self.enable_node_segment_label = enable_node_segment_label
        self.enable_best_route_selection = enable_best_route_selection
        self.v4_over_v6_nexthop = v4_over_v6_nexthop
        self.route_selection_algorithm = route_selection_algorithm
        self._static_unicast_routes: Dict[str, RibUnicastEntry] = {}
        self.best_routes_cache: Dict[str, RouteSelectionResult] = {}

    # -- static routes (SpfSolver.cpp:109-137) -----------------------------

    def update_static_unicast_routes(
        self,
        routes_to_update: Dict[str, RibUnicastEntry],
        routes_to_delete: List[str],
    ) -> None:
        for prefix, entry in routes_to_update.items():
            self._static_unicast_routes[prefix] = entry
        for prefix in routes_to_delete:
            self._static_unicast_routes.pop(prefix, None)

    def get_static_routes(self) -> Dict[str, RibUnicastEntry]:
        return self._static_unicast_routes

    # -- drain helpers (SpfSolver.cpp:512-556) -----------------------------

    @staticmethod
    def _filter_hard_drained_nodes(
        prefixes: PrefixEntries, area_link_states: Dict[str, LinkState]
    ) -> PrefixEntries:
        filtered = {
            na: e
            for na, e in prefixes.items()
            if not area_link_states[na[1]].is_node_overloaded(na[0])
        }
        # unless everything is hard-drained
        return filtered if filtered else prefixes

    @staticmethod
    def _get_soft_drained_nodes(
        prefixes: PrefixEntries, area_link_states: Dict[str, LinkState]
    ) -> Set[NodeAndArea]:
        return {
            na
            for na in prefixes
            if area_link_states[na[1]].get_node_metric_increment(na[0]) > 0
        }

    @staticmethod
    def _is_node_drained(
        node_area: NodeAndArea, area_link_states: Dict[str, LinkState]
    ) -> bool:
        node, area = node_area
        ls = area_link_states[area]
        return ls.is_node_overloaded(node) or ls.get_node_metric_increment(node) != 0

    # -- best route selection (SpfSolver.cpp:456-495) ----------------------

    def select_best_routes(
        self,
        prefix_entries: PrefixEntries,
        area_link_states: Dict[str, LinkState],
    ) -> RouteSelectionResult:
        assert prefix_entries, "no prefixes for best route selection"
        ret = RouteSelectionResult()
        filtered = self._filter_hard_drained_nodes(prefix_entries, area_link_states)
        soft_drained = self._get_soft_drained_nodes(prefix_entries, area_link_states)

        if self.enable_best_route_selection:
            ret.all_node_areas = select_routes(
                filtered, self.route_selection_algorithm, soft_drained
            )
            if not ret.all_node_areas:
                return ret
            ret.best_node_area = select_best_node_area(
                ret.all_node_areas, self.my_node_name
            )
        else:
            ret.all_node_areas = set(filtered)
            ret.best_node_area = min(ret.all_node_areas)

        ret.is_best_node_drained = self._is_node_drained(
            ret.best_node_area, area_link_states
        )
        return ret

    # -- nexthop computation (SpfSolver.cpp:649-768) -----------------------

    def get_next_hops_with_metric(
        self,
        dst_node_areas: Set[NodeAndArea],
        link_state: LinkState,
    ) -> Tuple[float, Dict[str, float]]:
        """Returns (min metric src→dest set, {nexthop node: distance from
        that nexthop to the dest})."""
        spf = link_state.get_spf_result(self.my_node_name)
        shortest = INF
        min_cost_nodes: Set[str] = set()
        for dst, _ in dst_node_areas:
            res = spf.get(dst)
            if res is None:
                continue
            if shortest >= res.metric:
                if shortest > res.metric:
                    shortest = res.metric
                    min_cost_nodes.clear()
                min_cost_nodes.add(dst)

        next_hop_nodes: Dict[str, float] = {}
        for dst in min_cost_nodes:
            for nh in spf[dst].next_hops:
                dist_nh = link_state.get_metric_from_a_to_b(self.my_node_name, nh)
                next_hop_nodes[nh] = shortest - (dist_nh or 0)
        return shortest, next_hop_nodes

    def get_next_hops(
        self,
        dst_node_areas: Set[NodeAndArea],
        is_v4: bool,
        best_metrics: Tuple[float, Dict[str, float]],
        swap_label: Optional[int],
        area: str,
        link_state: LinkState,
    ) -> Set[NextHop]:
        min_metric, next_hop_nodes = best_metrics
        assert next_hop_nodes
        next_hops: Set[NextHop] = set()
        for link in link_state.links_from_node(self.my_node_name):
            neighbor = link.get_other_node_name(self.my_node_name)
            if neighbor not in next_hop_nodes or not link.is_up():
                continue
            dist_over_link = link.get_max_metric() + next_hop_nodes[neighbor]
            if dist_over_link != min_metric:
                continue
            mpls_action = None
            if swap_label is not None:
                is_nh_also_dst = (neighbor, area) in dst_node_areas
                mpls_action = MplsAction(
                    MplsActionCode.PHP if is_nh_also_dst else MplsActionCode.SWAP,
                    swap_label=None if is_nh_also_dst else swap_label,
                )
            next_hops.add(
                NextHop(
                    address=(
                        link.get_nh_v4_from_node(self.my_node_name)
                        if is_v4 and not self.v4_over_v6_nexthop
                        else link.get_nh_v6_from_node(self.my_node_name)
                    ),
                    if_name=link.get_iface_from_node(self.my_node_name),
                    metric=int(dist_over_link),
                    area=link.area,
                    neighbor_node_name=neighbor,
                    mpls_action=mpls_action,
                )
            )
        return next_hops

    # -- per-prefix route creation (SpfSolver.cpp:161-312) -----------------

    def create_route_for_prefix(
        self,
        prefix: str,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[RibUnicastEntry]:
        is_v4 = prefix_is_v4(prefix)
        if is_v4 and not self.enable_v4 and not self.v4_over_v6_nexthop:
            return None
        self.best_routes_cache.pop(prefix, None)
        all_entries = prefix_state.prefixes().get(prefix)
        if not all_entries:
            return None

        # keep only entries from nodes reachable in their own area
        prefix_entries: PrefixEntries = {}
        local_prefix_considered = False
        for (node, parea), entry in all_entries.items():
            if node == self.my_node_name:
                local_prefix_considered = True
            ls = area_link_states.get(parea)
            if ls is None:
                continue
            spf = ls.get_spf_result(self.my_node_name)
            if node in spf:
                prefix_entries[(node, parea)] = entry
        if not prefix_entries:
            return None

        selection = self.select_best_routes(prefix_entries, area_link_states)
        if not selection.all_node_areas:
            return None
        self.best_routes_cache[prefix] = selection

        # local node advertises this prefix → nothing to program
        if selection.has_node(self.my_node_name):
            return None

        # which areas contain winners
        areas_with_best: Set[str] = {area for _, area in selection.all_node_areas}

        forwarding_algorithm = prefix_entries[
            min(selection.all_node_areas)
        ].forwarding_algorithm

        total_next_hops: Set[NextHop] = set()
        shortest_metric = INF
        for area in areas_with_best:
            link_state = area_link_states.get(area)
            if link_state is None:
                continue
            if forwarding_algorithm == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
                best_metric, nhs = self._select_best_paths_ksp2(
                    selection, prefix_entries, area, link_state, is_v4
                )
            else:
                best_metric, nhs = self._select_best_paths_spf(
                    selection, area, link_state, is_v4
                )
            if not nhs:
                continue
            # cross-area min-metric merge (SpfSolver.cpp:294-302)
            if shortest_metric >= best_metric:
                if shortest_metric > best_metric:
                    shortest_metric = best_metric
                    total_next_hops.clear()
                total_next_hops |= nhs

        return self._add_best_paths(
            prefix,
            selection,
            prefix_entries,
            total_next_hops,
            shortest_metric,
            local_prefix_considered,
        )

    def _select_best_paths_spf(
        self,
        selection: RouteSelectionResult,
        area: str,
        link_state: LinkState,
        is_v4: bool,
    ) -> Tuple[float, Set[NextHop]]:
        best_metrics = self.get_next_hops_with_metric(
            selection.all_node_areas, link_state
        )
        if not best_metrics[1]:
            return best_metrics[0], set()
        return best_metrics[0], self.get_next_hops(
            selection.all_node_areas, is_v4, best_metrics, None, area, link_state
        )

    def _select_best_paths_ksp2(
        self,
        selection: RouteSelectionResult,
        prefix_entries: PrefixEntries,
        area: str,
        link_state: LinkState,
        is_v4: bool,
    ) -> Tuple[float, Set[NextHop]]:
        """2-shortest edge-disjoint paths ECMP.

        For each winning dest, paths k=1 and k=2 from LinkState::getKthPaths.
        Nexthop = first link of each path; when the prefix's forwarding type
        is SR_MPLS, non-shortest paths are pinned with a PUSH label stack of
        the downstream nodes' segment labels (top = second hop).
        """
        paths: List[Tuple[Path, int]] = []
        for na in selection.all_node_areas:
            if na[1] != area:
                continue
            for k in (1, 2):
                for p in link_state.get_kth_paths(self.my_node_name, na[0], k):
                    if p:
                        paths.append((p, sum(l.get_max_metric() for l in p)))
        if not paths:
            return INF, set()

        use_mpls = (
            prefix_entries[min(selection.all_node_areas)].forwarding_type
            == PrefixForwardingType.SR_MPLS
        )
        adj_dbs = link_state.get_adjacency_databases()
        next_hops: Set[NextHop] = set()
        best_metric = min(cost for _, cost in paths)
        for path, cost in paths:
            first = path[0]
            neighbor = first.get_other_node_name(self.my_node_name)
            mpls_action = None
            if use_mpls and len(path) > 1:
                # label stack top-first: steer through each node past the
                # first hop using its node segment label
                labels = []
                cur = neighbor
                for link in path[1:]:
                    cur = link.get_other_node_name(cur)
                    db = adj_dbs.get(cur)
                    if db is not None and is_mpls_label_valid(db.node_label):
                        labels.append(db.node_label)
                if labels:
                    mpls_action = MplsAction(
                        MplsActionCode.PUSH, push_labels=tuple(labels)
                    )
            next_hops.add(
                NextHop(
                    address=(
                        first.get_nh_v4_from_node(self.my_node_name)
                        if is_v4 and not self.v4_over_v6_nexthop
                        else first.get_nh_v6_from_node(self.my_node_name)
                    ),
                    if_name=first.get_iface_from_node(self.my_node_name),
                    metric=int(cost),
                    area=area,
                    neighbor_node_name=neighbor,
                    mpls_action=mpls_action,
                )
            )
        return best_metric, next_hops

    def _add_best_paths(
        self,
        prefix: str,
        selection: RouteSelectionResult,
        prefix_entries: PrefixEntries,
        next_hops: Set[NextHop],
        shortest_metric: float,
        local_prefix_considered: bool,
    ) -> Optional[RibUnicastEntry]:
        """min-nexthop gate + entry construction (SpfSolver.cpp:596-640)."""
        if not next_hops:
            return None
        min_next_hop: Optional[int] = None
        for na in selection.all_node_areas:
            mh = prefix_entries[na].min_nexthop
            if mh is not None and (min_next_hop is None or mh > min_next_hop):
                min_next_hop = mh
        if min_next_hop is not None and min_next_hop > len(next_hops):
            return None

        entry = prefix_entries[selection.best_node_area]
        if selection.is_best_node_drained:
            entry = drained_entry(entry)
        return RibUnicastEntry(
            prefix=prefix,
            nexthops=next_hops,
            best_prefix_entry=entry,
            best_area=selection.best_node_area[1],
            igp_cost=shortest_metric,
            local_prefix_considered=local_prefix_considered,
        )

    # -- full build (SpfSolver.cpp:314-449) --------------------------------

    def build_route_db(
        self,
        area_link_states: Dict[str, LinkState],
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        if not any(ls.has_node(self.my_node_name) for ls in area_link_states.values()):
            return None
        route_db = DecisionRouteDb()
        self.best_routes_cache.clear()

        for prefix in prefix_state.prefixes():
            entry = self.create_route_for_prefix(
                prefix, area_link_states, prefix_state
            )
            if entry is not None:
                route_db.add_unicast_route(entry)

        # static routes: prefixState wins on conflict (SpfSolver.cpp:343-349)
        for prefix, sentry in self._static_unicast_routes.items():
            if prefix in route_db.unicast_routes:
                continue
            route_db.add_unicast_route(sentry)

        if self.enable_node_segment_label:
            self._build_node_label_routes(area_link_states, route_db)
        return route_db

    def _build_node_label_routes(
        self,
        area_link_states: Dict[str, LinkState],
        route_db: DecisionRouteDb,
    ) -> None:
        """MPLS routes for every node segment label
        (SpfSolver.cpp:354-445)."""
        label_to_node: Dict[int, Tuple[str, RibMplsEntry]] = {}
        for area, link_state in area_link_states.items():
            for node, adj_db in link_state.get_adjacency_databases().items():
                top_label = adj_db.node_label
                if top_label == 0 or not is_mpls_label_valid(top_label):
                    continue
                # label collision: the reference keeps the entry whose node
                # name is SMALLER (SpfSolver.cpp:389-392 skips the new entry
                # when existing < new; equal names from later areas replace)
                existing = label_to_node.get(top_label)
                if existing is not None and existing[0] < node:
                    continue
                if node == self.my_node_name:
                    label_to_node[top_label] = (
                        node,
                        RibMplsEntry(
                            top_label,
                            {
                                NextHop(
                                    address="::",
                                    area=area,
                                    mpls_action=MplsAction(
                                        MplsActionCode.POP_AND_LOOKUP
                                    ),
                                )
                            },
                        ),
                    )
                    continue
                metric_nhs = self.get_next_hops_with_metric(
                    {(node, area)}, link_state
                )
                if not metric_nhs[1]:
                    continue
                label_to_node[top_label] = (
                    node,
                    RibMplsEntry(
                        top_label,
                        self.get_next_hops(
                            {(node, area)},
                            False,
                            metric_nhs,
                            top_label,
                            area,
                            link_state,
                        ),
                    ),
                )
        for _, (_, entry) in label_to_node.items():
            route_db.add_mpls_route(entry)
