"""RibPolicy — TTL'd nexthop-weight policy applied to the computed RIB.

Reference: openr/decision/RibPolicy.{h,cpp}: a policy is a list of
statements, each matching routes (by prefix or tag) and applying an
action that sets per-nexthop weights (default / per-area / per-neighbor;
weight 0 drops the nexthop).  The policy carries a TTL and is persisted
by Decision (Decision.cpp:634-708) so it survives restarts until expiry.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from openr_tpu.common.runtime import Clock
from openr_tpu.decision.rib import DecisionRouteDb, RibUnicastEntry
from openr_tpu.types import NextHop


@dataclass
class RibRouteActionWeight:
    """if/OpenrCtrl.thrift RibRouteActionWeight."""

    default_weight: int = 1
    area_to_weight: Dict[str, int] = field(default_factory=dict)
    neighbor_to_weight: Dict[str, int] = field(default_factory=dict)


@dataclass
class RibPolicyStatement:
    """Match (prefixes OR tags) → action (RibPolicy.h:24-80)."""

    name: str = ""
    prefixes: List[str] = field(default_factory=list)
    tags: Set[str] = field(default_factory=set)
    action: RibRouteActionWeight = field(default_factory=RibRouteActionWeight)

    def matches(self, entry: RibUnicastEntry) -> bool:
        if self.prefixes and entry.prefix in self.prefixes:
            return True
        if self.tags and self.tags & entry.best_prefix_entry.tags:
            return True
        return False

    def apply_action(self, entry: RibUnicastEntry) -> bool:
        """Re-weight nexthops in place; weight 0 drops.  Returns True if
        the entry changed (RibPolicyStatement::applyAction)."""
        new_nexthops = set()
        changed = False
        for nh in entry.nexthops:
            w = self.action.neighbor_to_weight.get(
                nh.neighbor_node_name,
                self.action.area_to_weight.get(
                    nh.area, self.action.default_weight
                ),
            )
            if w == 0:
                changed = True
                continue
            if w != nh.weight:
                changed = True
                nh = NextHop(
                    address=nh.address,
                    if_name=nh.if_name,
                    metric=nh.metric,
                    weight=w,
                    area=nh.area,
                    neighbor_node_name=nh.neighbor_node_name,
                    mpls_action=nh.mpls_action,
                )
            new_nexthops.add(nh)
        if changed:
            entry.nexthops = new_nexthops
        return changed


@dataclass
class RibPolicy:
    statements: List[RibPolicyStatement] = field(default_factory=list)
    #: absolute expiry on the shared clock; None = no policy
    valid_until: float = 0.0

    def is_active(self, clock: Clock) -> bool:
        return clock.now() < self.valid_until

    def apply_policy(self, route_db: DecisionRouteDb, clock: Clock) -> int:
        """Apply to every matching route; returns number modified
        (RibPolicy::applyPolicy, used in Decision.cpp:917-950)."""
        if not self.is_active(clock):
            return 0
        modified = 0
        for entry in route_db.unicast_routes.values():
            for stmt in self.statements:
                if stmt.matches(entry):
                    if stmt.apply_action(entry):
                        modified += 1
                    break  # first matching statement wins
        # drop routes whose nexthops were all zero-weighted
        for prefix in [
            p for p, e in route_db.unicast_routes.items() if not e.nexthops
        ]:
            del route_db.unicast_routes[prefix]
            modified += 1
        return modified

    # -- persistence (FLAGS_rib_policy_file pattern) -----------------------

    def to_json(self, clock: Clock) -> str:
        return json.dumps(
            {
                "ttl_remaining_s": max(0.0, self.valid_until - clock.now()),
                "statements": [
                    {
                        "name": s.name,
                        "prefixes": s.prefixes,
                        "tags": sorted(s.tags),
                        "action": {
                            "default_weight": s.action.default_weight,
                            "area_to_weight": s.action.area_to_weight,
                            "neighbor_to_weight": s.action.neighbor_to_weight,
                        },
                    }
                    for s in self.statements
                ],
            }
        )

    @classmethod
    def from_json(cls, text: str, clock: Clock) -> Optional["RibPolicy"]:
        d = json.loads(text)
        ttl = d.get("ttl_remaining_s", 0.0)
        if ttl <= 0:
            return None
        return cls(
            statements=[
                RibPolicyStatement(
                    name=s.get("name", ""),
                    prefixes=list(s.get("prefixes", [])),
                    tags=set(s.get("tags", [])),
                    action=RibRouteActionWeight(
                        default_weight=s["action"].get("default_weight", 1),
                        area_to_weight=dict(s["action"].get("area_to_weight", {})),
                        neighbor_to_weight=dict(
                            s["action"].get("neighbor_to_weight", {})
                        ),
                    ),
                )
                for s in d.get("statements", [])
            ],
            valid_until=clock.now() + ttl,
        )
