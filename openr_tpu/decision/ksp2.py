"""Device-backed KSP2_ED_ECMP: batched masked re-solves + host path trace.

The reference computes the k-th edge-disjoint shortest paths by re-running
full Dijkstra with the links of paths 1..k-1 ignored, once per destination
(LinkState.cpp:675-699) — on a fat-tree where every rack prefix uses
KSP2_ED_ECMP that is O(destinations) host Dijkstras per rebuild, the hot
loop.  Here the re-solves run as ONE batched device call
(``batched_spf_distances_masked``: vmapped masked Bellman-Ford over a
[U, E] ignore-mask batch), and only the cheap part — greedy path tracing
over the shortest-path DAG (traceOnePath, LinkState.cpp:227-247) — stays
on the host, reconstructed from the device distance fields.

Exactness: ``LinkState.run_spf`` iterates sorted adjacency, so its
``path_links`` order is (settle-order of predecessor, link order) — the
reconstruction here sorts by exactly that key, making the greedy trace
bit-identical to the scalar path.  The traced paths are seeded into the
LinkState k-path memo (``seed_kth_paths``), after which the unmodified
scalar KSP2 selection chain (SpfSolver._select_best_paths_ksp2, SR-MPLS
label stacks, cross-area merge, min-nexthop gate) runs without any host
Dijkstra.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from openr_tpu.decision.link_state import Link, LinkState, Path
from openr_tpu.ops.csr import EncodedTopology, link_failure_batch

_BIG = np.float32(3.4e38)


class Ksp2DeviceEngine:
    """Per-(area LinkState, encoded topology) KSP2 seeding engine.

    ``seed(dests)`` guarantees ``link_state.get_kth_paths(root, d, k)`` for
    k in (1, 2) is memoized for every d in dests without running host
    Dijkstra for the k=2 re-solves.  Results live in the LinkState memo, so
    repeat rebuilds on an unchanged topology are free; the memo is cleared
    by LinkState on topology change, which re-arms this engine.
    """

    def __init__(
        self, link_state: LinkState, topo: EncodedTopology, root: str
    ) -> None:
        self.link_state = link_state
        self.topo = topo
        self.root = root
        self._link_id: Dict[Tuple[str, str, str, str], int] = {
            link.key: i for i, link in enumerate(topo.links)
        }
        self.num_device_batches = 0
        self.num_seeded = 0

    # -- public entry ------------------------------------------------------

    def seed(self, dests: Sequence[str]) -> None:
        ls = self.link_state
        root = self.root
        todo = [
            d
            for d in dict.fromkeys(dests)  # stable de-dup
            if d != root and not ls.has_kth_paths(root, d, 2)
        ]
        if not todo:
            return
        # k=1: trace over the (memoized) base SPF — cheap, scalar-exact
        ignore_ids: List[List[int]] = []
        for d in todo:
            ignored: Set[Link] = set()
            for path in ls.get_kth_paths(root, d, 1):
                ignored.update(path)
            ignore_ids.append(sorted(self._link_id[l.key] for l in ignored))

        dist2 = self._device_resolve(ignore_ids)
        for row, d in enumerate(todo):
            ignored_links = {
                self.topo.links[i] for i in ignore_ids[row]
            }
            paths = self._trace_all(d, dist2[row], ignored_links)
            ls.seed_kth_paths(root, d, 2, paths)
            self.num_seeded += 1

    # -- device batch ------------------------------------------------------

    #: destination-batch buckets: the jit cache must stay warm across
    #: rebuilds where the number of un-memoized destinations varies
    #: (prefix churn re-arms a few dests at a time) — same discipline as
    #: node_buckets/cand_buckets in the encoder
    BATCH_BUCKETS = (8, 32, 128, 512, 2048, 8192, 32768)

    def _device_resolve(self, ignore_ids: List[List[int]]) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        from openr_tpu.ops.csr import bucket_for
        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.ops.spf import batched_spf_distances_masked

        topo = self.topo
        n = len(ignore_ids)
        padded = bucket_for(n, self.BATCH_BUCKETS)
        # padding rows solve the unmasked topology (cheap no-op work)
        ignore_ids = ignore_ids + [[]] * (padded - n)
        masks = link_failure_batch(topo, ignore_ids)
        roots = np.full(padded, topo.node_id(self.root), np.int32)
        dist = call_jit_guarded(
            batched_spf_distances_masked,
            jnp.asarray(topo.src),
            jnp.asarray(topo.dst),
            jnp.asarray(topo.w),
            jnp.asarray(topo.edge_ok),
            jnp.asarray(masks),
            jnp.asarray(topo.overloaded),
            jnp.asarray(roots),
        )
        self.num_device_batches += 1
        # one host fetch for the whole batch (round trips dominate on a
        # tunneled device; see backend.py)
        return np.asarray(jax.device_get(dist))[:n]

    # -- host trace over the device distance field -------------------------

    def _path_links(
        self,
        node: str,
        dist: np.ndarray,
        ignored: Set[Link],
    ) -> List[Tuple[Link, str]]:
        """Reconstruct NodeSpfResult.path_links for `node` in run_spf's
        append order: predecessors settle in (metric, name) heap order and
        each relaxes its sorted links (run_spf iterates
        ordered_links_from_node), so the key is (dist[prev], prev, link)."""
        ls = self.link_state
        ids = self.topo.node_ids
        dv = dist[ids[node]]
        out: List[Tuple[np.float32, str, Link]] = []
        for link in ls.ordered_links_from_node(node):
            prev = link.get_other_node_name(node)
            if not link.is_up() or link in ignored:
                continue
            if ls.is_node_overloaded(prev) and prev != self.root:
                continue
            du = dist[ids[prev]]
            if du >= _BIG:
                continue
            if np.float32(du + np.float32(link.get_max_metric())) == dv:
                out.append((du, prev, link))
        out.sort(key=lambda t: (t[0], t[1], t[2].key))
        return [(link, prev) for _, prev, link in out]

    def _trace_all(
        self, dest: str, dist: np.ndarray, ignored: Set[Link]
    ) -> List[Path]:
        if dist[self.topo.node_id(dest)] >= _BIG:
            return []
        visited: Set[Link] = set()
        pl_cache: Dict[str, List[Tuple[Link, str]]] = {}

        def path_links(v: str) -> List[Tuple[Link, str]]:
            cached = pl_cache.get(v)
            if cached is None:
                cached = pl_cache[v] = self._path_links(v, dist, ignored)
            return cached

        def trace_one(v: str) -> Optional[Path]:
            # mirrors LinkState._trace_one_path exactly
            if v == self.root:
                return []
            for link, prev in path_links(v):
                if link in visited:
                    continue
                visited.add(link)
                sub = trace_one(prev)
                if sub is not None:
                    sub.append(link)
                    return sub
            return None

        paths: List[Path] = []
        path = trace_one(dest)
        while path:
            paths.append(path)
            path = trace_one(dest)
        return paths
