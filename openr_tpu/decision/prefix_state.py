"""PrefixState: prefix → {(node, area) → PrefixEntry} map
(reference: openr/decision/PrefixState.{h,cpp}).

update/delete return the set of prefixes whose candidate set changed, which
Decision uses to drive incremental rebuilds.
"""

from __future__ import annotations

from typing import Dict, Set, Tuple

from openr_tpu.types import PrefixEntry

NodeAndArea = Tuple[str, str]


class PrefixState:
    def __init__(self) -> None:
        self._prefixes: Dict[str, Dict[NodeAndArea, PrefixEntry]] = {}

    def prefixes(self) -> Dict[str, Dict[NodeAndArea, PrefixEntry]]:
        return self._prefixes

    def get_received_routes_count(self) -> int:
        return sum(len(m) for m in self._prefixes.values())

    def update_prefix(
        self, node: str, area: str, entry: PrefixEntry
    ) -> Set[str]:
        """Insert/replace one advertisement; returns changed prefixes
        (PrefixState::updatePrefix, PrefixState.cpp)."""
        if self.update_prefix_changed(node, area, entry):
            return {entry.prefix}
        return set()

    def update_prefix_changed(
        self, node: str, area: str, entry: PrefixEntry
    ) -> bool:
        """update_prefix without the per-call set allocation — the bulk
        ingest path calls this half a million times on cold boot."""
        key: NodeAndArea = (node, area)
        entries = self._prefixes.setdefault(entry.prefix, {})
        prior = entries.get(key)
        if prior == entry:
            return False
        entries[key] = entry
        return True

    def delete_prefix(self, node: str, area: str, prefix: str) -> Set[str]:
        """Remove one advertisement; returns changed prefixes."""
        key: NodeAndArea = (node, area)
        entries = self._prefixes.get(prefix)
        if entries is None or key not in entries:
            return set()
        del entries[key]
        if not entries:
            del self._prefixes[prefix]
        return {prefix}

    def delete_all_for_node(self, node: str, area: str) -> Set[str]:
        """Drop every advertisement from (node, area) — node left the area."""
        changed: Set[str] = set()
        for prefix in list(self._prefixes):
            changed |= self.delete_prefix(node, area, prefix)
        return changed

    def has_prefix(self, prefix: str) -> bool:
        return prefix in self._prefixes
