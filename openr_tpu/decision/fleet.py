"""Fleet RIB engine: every node's what-if RouteDb from one device batch.

The ctrl API's getRouteDbComputed answers "what routes would node X
compute?" — the reference runs a fresh scalar SpfSolver pass per call
(Decision.cpp:342), so a fleet-wide sweep costs |V| sequential
Dijkstras.  Here all vantage points are one batched device solve
(ops/fleet_tables.py: root = a batch dim over the multi-area SPF +
selection kernels, with per-area absence masked exactly like the scalar
semantics); tables are cached until the LSDB changes, and each ctrl
request decodes ONLY its root — through the SAME decode path the
Decision backend uses (backend._decode_rows), so fleet results can
never drift from the live RouteDb semantics.

Eligibility (else the scalar path runs, exactness preserved):
SHORTEST_DISTANCE or PER_AREA_SHORTEST_DISTANCE with best-route
selection, and no KSP2_ED_ECMP advertisements (the k-path trace is
per-root host work the batch can't amortize yet).  Multi-area LSDBs are
first-class: cross-area min-metric merge happens in decode, per-area
participation comes from each root's per-area symbol-table presence.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from openr_tpu.decision.rib import DecisionRouteDb
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    RouteComputationRules,
    prefix_is_v4,
)

ROOT_CHUNK = 1024


class FleetRibEngine:
    """Caches all-roots selection tables per LSDB change generation."""

    def __init__(
        self, solver: SpfSolver, mesh=None, pool=None, probe=None
    ) -> None:
        """``mesh``: optional ``jax.sharding.Mesh`` with a ``batch``
        axis — the vantage-root batch then shards across the mesh
        (ops.fleet_tables.sharded_fleet_tables), bit-identical to the
        single-device kernel.  ``pool``: optional
        :class:`~openr_tpu.parallel.mesh.DevicePool` — root chunks then
        spread as committed per-device dispatches over the pool's
        HEALTHY chips (the health-governed data-parallel path: a
        quarantined chip's share re-packs onto the survivors on the
        next solve, with no shard_map requirement).  ``probe``: optional
        :class:`~openr_tpu.tracing.pipeline.PipelineProbe` — fleet
        solves then record the same phase histograms / per-chip busy
        gauges route builds do (Decision shares the backend's probe so
        the whole dispatch plane lands on one ledger)."""
        from openr_tpu.tracing.pipeline import disabled_probe

        self.solver = solver  # settings template (v4 flags, labels, algo)
        self.mesh = mesh
        self.pool = pool
        self.probe = probe if probe is not None else disabled_probe()
        self._cache_key = None
        self._state = None  # dict of cached tables + decode context
        self._ksp2_scan = None  # (change_seq, result)
        self.num_batched_solves = 0
        self.num_decodes = 0
        self.num_pool_dispatches = 0

    # -- eligibility -------------------------------------------------------

    def eligible(self, area_link_states, prefix_state, change_seq) -> bool:
        if not area_link_states:
            return False
        s = self.solver
        if not s.enable_best_route_selection or s.route_selection_algorithm not in (
            RouteComputationRules.SHORTEST_DISTANCE,
            RouteComputationRules.PER_AREA_SHORTEST_DISTANCE,
        ):
            return False
        # the O(P*C) KSP2 scan is cached on the same change generation
        # as the tables — ctrl requests between LSDB changes skip it
        if self._ksp2_scan is not None and self._ksp2_scan[0] == change_seq:
            return self._ksp2_scan[1]
        ok = not any(
            entry.forwarding_algorithm
            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
            for entries in prefix_state.prefixes().values()
            for entry in entries.values()
        )
        self._ksp2_scan = (change_seq, ok)
        return ok

    # -- table computation (cached) ---------------------------------------

    def _tables_for(self, area_link_states, prefix_state, change_seq):
        import jax
        import jax.numpy as jnp

        from openr_tpu.decision.backend import DEGREE_BUCKETS
        from openr_tpu.decision.cand_table import CandidateTable
        from openr_tpu.ops.csr import bucket_for, encode_multi_area
        from openr_tpu.ops.fleet_tables import fleet_multi_area_tables
        from openr_tpu.ops.jit_guard import call_jit_guarded

        key = (
            tuple(
                (a, area_link_states[a].topology_seq)
                for a in sorted(area_link_states)
            ),
            change_seq,
        )
        if self._cache_key == key and self._state is not None:
            return self._state
        from openr_tpu.tracing import pipeline

        me = self.solver.my_node_name
        with self.probe.phase(pipeline.ENCODE):
            enc = encode_multi_area(area_link_states, me)
        with self.probe.phase(pipeline.HOST_FETCH):
            table = CandidateTable()
            table.full_sync(prefix_state)
            dv = table.derived(enc)
            # every node participating in ANY area gets a vantage row
            names = sorted(
                set().union(*[set(t.node_ids) for t in enc.topos])
            )
            roots_mat = np.asarray(
                [[t.node_ids.get(n, -1) for t in enc.topos] for n in names],
                np.int32,
            )
        D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
        per_area = (
            self.solver.route_selection_algorithm
            == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
        )
        with self.probe.phase(pipeline.TRANSFER):
            dev = dict(
                src=jnp.asarray(enc.src),
                dst=jnp.asarray(enc.dst),
                w=jnp.asarray(enc.w),
                edge_ok=jnp.asarray(enc.edge_ok),
                overloaded=jnp.asarray(enc.overloaded),
                soft=jnp.asarray(enc.soft),
                cand_area=jnp.asarray(dv.cand_area),
                cand_node=jnp.asarray(dv.cand_node),
                cand_ok=jnp.asarray(dv.cand_ok),
                drain_metric=jnp.asarray(dv.drain_metric),
                path_pref=jnp.asarray(dv.path_pref),
                source_pref=jnp.asarray(dv.source_pref),
                distance=jnp.asarray(dv.distance),
                cand_node_in_area=jnp.asarray(dv.cand_node_in_area),
            )
        B = len(names)
        P, C = dv.cand_ok.shape
        A = enc.num_areas
        use = np.empty((B, P, C), bool)
        shortest = np.empty((B, P, A), np.float32)
        lanes = np.empty((B, P, A, D), bool)
        valid = np.empty((B, P, A), bool)
        mesh_n = self.mesh.devices.size if self.mesh is not None else 1
        if self.mesh is not None:
            from openr_tpu.ops.fleet_tables import sharded_fleet_tables
            from openr_tpu.parallel.mesh import batch_sharding, replicated

            rep = replicated(self.mesh)
            dev = {k: jax.device_put(v, rep) for k, v in dev.items()}
            fleet_fn = sharded_fleet_tables(self.mesh, D, per_area)
            roots_sh = batch_sharding(self.mesh)
        # pool path (no shard_map needed): root chunks spread round-robin
        # over the pool's HEALTHY chips as committed per-device
        # dispatches — a quarantined chip's share re-packs onto the
        # survivors on the next solve
        pool_devs = None
        chunk_rows = ROOT_CHUNK
        per_dev_args: dict = {}
        if self.mesh is None and self.pool is not None:
            healthy = self.pool.healthy_indices()
            if len(healthy) > 1:
                pool_devs = healthy
                chunk_rows = min(
                    ROOT_CHUNK, max(32, -(-B // len(healthy)))
                )

        def args_on(idx):
            if idx not in per_dev_args:
                d = self.pool.device(idx)
                with self.probe.phase(pipeline.TRANSFER, device=idx):
                    per_dev_args[idx] = {
                        k: jax.device_put(v, d) for k, v in dev.items()
                    }
            return per_dev_args[idx]

        from openr_tpu.ops import jit_guard

        # dispatch every root chunk, then fetch ALL of them with one
        # device_get (async-copies each leaf before blocking): the whole
        # fleet build costs a single overlapped host round trip instead
        # of one per chunk
        pending: list = []
        used_devices: set = set()
        for off in range(0, B, chunk_rows):
            chunk = roots_mat[off : off + chunk_rows]
            with self.probe.phase(pipeline.PAD_PACK):
                b = 1 << max(5, (len(chunk) - 1).bit_length())  # pow2
                b = ((b + mesh_n - 1) // mesh_n) * mesh_n  # whole shards
                padded = np.full((b, A), -1, np.int32)
                padded[: len(chunk)] = chunk
            # a fully -1 pad row would make SPF roots all-absent: fine
            if self.mesh is not None:
                with self.probe.phase(pipeline.DEVICE_COMPUTE):
                    out = fleet_fn(
                        jax.device_put(padded, roots_sh),
                        dev["src"],
                        dev["dst"],
                        dev["w"],
                        dev["edge_ok"],
                        dev["overloaded"],
                        dev["soft"],
                        dev["cand_area"],
                        dev["cand_node"],
                        dev["cand_ok"],
                        dev["drain_metric"],
                        dev["path_pref"],
                        dev["source_pref"],
                        dev["distance"],
                        dev["cand_node_in_area"],
                    )
            elif pool_devs is not None:
                idx = pool_devs[(off // chunk_rows) % len(pool_devs)]
                args = args_on(idx)
                with self.probe.phase(pipeline.TRANSFER, device=idx):
                    roots_dev = jax.device_put(
                        jnp.asarray(padded), self.pool.device(idx)
                    )
                with self.probe.phase(
                    pipeline.DEVICE_COMPUTE, device=idx
                ), jit_guard.dispatch_device(idx):
                    out = call_jit_guarded(
                        fleet_multi_area_tables,
                        roots=roots_dev,
                        max_degree=D,
                        per_area_distance=per_area,
                        **args,
                    )
                self.pool.note_dispatch(idx)
                used_devices.add(idx)
                self.num_pool_dispatches += 1
            else:
                with self.probe.phase(pipeline.DEVICE_COMPUTE, device=0):
                    out = call_jit_guarded(
                        fleet_multi_area_tables,
                        roots=jnp.asarray(padded),
                        max_degree=D,
                        per_area_distance=per_area,
                        **dev,
                    )
                used_devices.add(0)
            pending.append((off, len(chunk), out))
        with self.probe.phase(
            pipeline.DEVICE_GET, devices=sorted(used_devices)
        ):
            fetched = jax.device_get([p[2] for p in pending])
        for (off, n, _out), (u, s_, l, v) in zip(pending, fetched):
            use[off : off + n] = u[:n]
            shortest[off : off + n] = s_[:n]
            lanes[off : off + n] = l[:n]
            valid[off : off + n] = v[:n]
        self._state = dict(
            enc=enc,
            dv=dv,
            table=table,
            names=names,
            index={n: i for i, n in enumerate(names)},
            use=use,
            shortest=shortest,
            lanes=lanes,
            valid=valid,
        )
        self._cache_key = key
        self.num_batched_solves += 1
        return self._state

    # -- per-root decode (the backend's own decode path) -------------------

    def compute_for_node(
        self, node: str, area_link_states, prefix_state, change_seq
    ) -> Optional[DecisionRouteDb]:
        """The RouteDb `node` would compute, decoded from the cached
        batch tables; None when node is unknown (caller falls back)."""
        from openr_tpu.decision.backend import TpuBackend

        from openr_tpu.tracing import pipeline

        st = self._tables_for(area_link_states, prefix_state, change_seq)
        ri = st["index"].get(node)
        if ri is None:
            return None
        self.num_decodes += 1
        tb = TpuBackend(self._vantage_solver(node))
        table = st["table"]
        with self.probe.phase(pipeline.DECODE):
            row_items = [
                (int(r), table.row_prefix[r])
                for r in np.nonzero(st["use"][ri].any(axis=1))[0]
                if table.row_prefix[r] is not None
            ]
            results = tb._decode_rows(
                row_items,
                st["use"][ri],
                st["shortest"][ri],
                st["lanes"][ri],
                st["valid"][ri],
                st["dv"],
                None,
                st["enc"],
                area_link_states,
                prefix_state,
            )
            db = DecisionRouteDb()
            for _prefix, entry in sorted(results.items()):
                if entry is not None:
                    db.add_unicast_route(entry)
            if self.solver.enable_node_segment_label:
                tb.solver._build_node_label_routes(area_link_states, db)
        return db

    def _vantage_solver(self, node: str) -> SpfSolver:
        s = self.solver
        return SpfSolver(
            node,
            enable_v4=s.enable_v4,
            enable_node_segment_label=s.enable_node_segment_label,
            enable_best_route_selection=s.enable_best_route_selection,
            v4_over_v6_nexthop=s.v4_over_v6_nexthop,
            route_selection_algorithm=s.route_selection_algorithm,
        )

    # -- fleet summary -----------------------------------------------------

    def fleet_summary(
        self, area_link_states, prefix_state, change_seq
    ) -> Dict[str, dict]:
        """Per-node unicast route counts + total nexthops from ONE batch
        solve — the 'what does every router see' operator view.  Applies
        the same host-side gates the decode applies (v4 family,
        skip-if-self, min-nexthop over the cross-area merge) so counts
        always match compute_for_node."""
        st = self._tables_for(area_link_states, prefix_state, change_seq)
        dv, table = st["dv"], st["table"]
        use, shortest, lanes, valid = (
            st["use"],
            st["shortest"],
            st["lanes"],
            st["valid"],
        )
        B, P, A = valid.shape

        include = np.asarray(
            [
                p is not None
                and (
                    self.solver.enable_v4
                    or self.solver.v4_over_v6_nexthop
                    or not prefix_is_v4(p)
                )
                for p in table.row_prefix
            ],
            bool,
        )  # [P]
        # cross-area min-metric merge, vectorized (SpfSolver.cpp:276-302)
        m = np.where(valid, shortest, np.inf)  # [B, P, A]
        m_star = m.min(axis=2)  # [B, P]
        at_min = valid & (m == m_star[:, :, None])
        num_nh_area = lanes.sum(axis=3)  # [B, P, A]
        merged = (num_nh_area * at_min).sum(axis=2)  # [B, P]
        # per-root gates, matching the backend decode exactly:
        #   min-nexthop req = max over THIS root's selection winners
        #   (not all candidates — a losing advertiser's requirement must
        #   not gate the winner's route)
        #   skip-if-self by GLOBAL candidate identity (adv_gid interned
        #   per advertiser name; a never-advertising root has no gid and
        #   can never self-win)
        adv_gid = table.adv_gid  # [P, C] (-1 = empty slot)
        gid_of = table._node_gid
        self_win = np.zeros((B, P), bool)
        req = np.zeros((B, P), np.int32)
        for i, name in enumerate(st["names"]):
            req[i] = np.max(np.where(use[i], dv.min_nexthop, 0), axis=1)
            g = gid_of.get(name)
            if g is not None:
                self_win[i] = (use[i] & (adv_gid == g)).any(axis=1)
        route_ok = (
            include[None, :]
            & valid.any(axis=2)
            & ~self_win
            & (merged > 0)
            & (merged >= req)
        )
        out = {}
        for i, name in enumerate(st["names"]):
            out[name] = {
                "num_routes": int(route_ok[i].sum()),
                "total_nexthops": int(merged[i][route_ok[i]].sum()),
            }
        return out
