"""Fleet RIB engine: every node's what-if RouteDb from one device batch.

The ctrl API's getRouteDbComputed answers "what routes would node X
compute?" — the reference runs a fresh scalar SpfSolver pass per call
(Decision.cpp:342), so a fleet-wide sweep costs |V| sequential
Dijkstras.  Here all vantage points are one batched device solve
(ops/fleet_tables.py: root = a batch dim over the multi-area SPF +
selection kernels, with per-area absence masked exactly like the scalar
semantics); tables are cached until the LSDB changes, and each ctrl
request decodes ONLY its root — through the SAME decode path the
Decision backend uses (backend._decode_rows), so fleet results can
never drift from the live RouteDb semantics.

Eligibility (else the scalar path runs, exactness preserved):
SHORTEST_DISTANCE or PER_AREA_SHORTEST_DISTANCE with best-route
selection, and no KSP2_ED_ECMP advertisements (the k-path trace is
per-root host work the batch can't amortize yet).  Multi-area LSDBs are
first-class: cross-area min-metric merge happens in decode, per-area
participation comes from each root's per-area symbol-table presence.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from openr_tpu.decision.rib import DecisionRouteDb
from openr_tpu.decision.spf_solver import SpfSolver
from openr_tpu.types import (
    PrefixForwardingAlgorithm,
    RouteComputationRules,
    prefix_is_v4,
)

ROOT_CHUNK = 1024


class FleetRibEngine:
    """Caches all-roots selection tables per LSDB change generation."""

    def __init__(
        self, solver: SpfSolver, mesh=None, pool=None, probe=None
    ) -> None:
        """``mesh``: optional ``jax.sharding.Mesh`` with a ``batch``
        axis — the vantage-root batch then shards across the mesh
        (ops.fleet_tables.sharded_fleet_tables), bit-identical to the
        single-device kernel.  ``pool``: optional
        :class:`~openr_tpu.parallel.mesh.DevicePool` — root chunks then
        spread as committed per-device dispatches over the pool's
        HEALTHY chips (the health-governed data-parallel path: a
        quarantined chip's share re-packs onto the survivors on the
        next solve, with no shard_map requirement).  ``probe``: optional
        :class:`~openr_tpu.tracing.pipeline.PipelineProbe` — fleet
        solves then record the same phase histograms / per-chip busy
        gauges route builds do (Decision shares the backend's probe so
        the whole dispatch plane lands on one ledger)."""
        from openr_tpu.tracing.pipeline import disabled_probe

        self.solver = solver  # settings template (v4 flags, labels, algo)
        self.mesh = mesh
        self.pool = pool
        self.probe = probe if probe is not None else disabled_probe()
        self._cache_key = None
        self._state = None  # dict of cached tables + decode context
        self._ksp2_scan = None  # (change_seq, result)
        #: pool health generation the collective mesh was derived under
        #: (PR-6 remnant: engines given BOTH a mesh and a pool re-derive
        #: the mesh from DevicePool.survivor_mesh() whenever the healthy
        #: set changes, so the shard_map-collective path re-packs on
        #: chip quarantine exactly like the committed-dispatch path)
        self._mesh_health_seq = None
        self._mesh_requested = mesh is not None
        #: previous generation's delta base (device-resident chunk
        #: outputs + host tables + kernel-input pins)
        self._prev_gen = None
        self.num_batched_solves = 0
        self.num_decodes = 0
        self.num_pool_dispatches = 0
        self.num_delta_solves = 0
        self.num_delta_roots_fetched = 0
        self.num_delta_roots_skipped = 0

    def _active_mesh(self):
        """The collective mesh for this solve.  With no pool, the
        constructor's mesh is pinned.  With a pool, the mesh re-derives
        from ``DevicePool.survivor_mesh()`` on every health transition:
        a chip quarantine re-packs the collective onto the survivors
        (or, when fewer than two chips survive / shard_map is
        unavailable, drops to the committed-dispatch pool path), and a
        restore re-admits the chip."""
        if not self._mesh_requested:
            return None
        if self.pool is None:
            return self.mesh
        if self._mesh_health_seq != self.pool.health_seq:
            self.mesh = self.pool.survivor_mesh()
            self._mesh_health_seq = self.pool.health_seq
        return self.mesh

    # -- eligibility -------------------------------------------------------

    def eligible(self, area_link_states, prefix_state, change_seq) -> bool:
        if not area_link_states:
            return False
        s = self.solver
        if not s.enable_best_route_selection or s.route_selection_algorithm not in (
            RouteComputationRules.SHORTEST_DISTANCE,
            RouteComputationRules.PER_AREA_SHORTEST_DISTANCE,
        ):
            return False
        # the O(P*C) KSP2 scan is cached on the same change generation
        # as the tables — ctrl requests between LSDB changes skip it
        if self._ksp2_scan is not None and self._ksp2_scan[0] == change_seq:
            return self._ksp2_scan[1]
        ok = not any(
            entry.forwarding_algorithm
            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
            for entries in prefix_state.prefixes().values()
            for entry in entries.values()
        )
        self._ksp2_scan = (change_seq, ok)
        return ok

    # -- table computation (cached) ---------------------------------------

    def _tables_for(self, area_link_states, prefix_state, change_seq):
        import jax
        import jax.numpy as jnp

        from openr_tpu.decision.backend import DEGREE_BUCKETS
        from openr_tpu.decision.cand_table import CandidateTable
        from openr_tpu.ops.csr import bucket_for, encode_multi_area
        from openr_tpu.ops.fleet_tables import fleet_multi_area_tables
        from openr_tpu.ops.jit_guard import call_jit_guarded

        key = (
            tuple(
                (a, area_link_states[a].topology_seq)
                for a in sorted(area_link_states)
            ),
            change_seq,
        )
        if self._cache_key == key and self._state is not None:
            return self._state
        from openr_tpu.tracing import pipeline

        me = self.solver.my_node_name
        with self.probe.phase(pipeline.ENCODE):
            enc = encode_multi_area(area_link_states, me)
        with self.probe.phase(pipeline.HOST_FETCH):
            table = CandidateTable()
            table.full_sync(prefix_state)
            dv = table.derived(enc)
            # every node participating in ANY area gets a vantage row
            names = sorted(
                set().union(*[set(t.node_ids) for t in enc.topos])
            )
            roots_mat = np.asarray(
                [[t.node_ids.get(n, -1) for t in enc.topos] for n in names],
                np.int32,
            )
        D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
        per_area = (
            self.solver.route_selection_algorithm
            == RouteComputationRules.PER_AREA_SHORTEST_DISTANCE
        )
        with self.probe.phase(pipeline.TRANSFER):
            dev = dict(
                src=jnp.asarray(enc.src),
                dst=jnp.asarray(enc.dst),
                w=jnp.asarray(enc.w),
                edge_ok=jnp.asarray(enc.edge_ok),
                overloaded=jnp.asarray(enc.overloaded),
                soft=jnp.asarray(enc.soft),
                cand_area=jnp.asarray(dv.cand_area),
                cand_node=jnp.asarray(dv.cand_node),
                cand_ok=jnp.asarray(dv.cand_ok),
                drain_metric=jnp.asarray(dv.drain_metric),
                path_pref=jnp.asarray(dv.path_pref),
                source_pref=jnp.asarray(dv.source_pref),
                distance=jnp.asarray(dv.distance),
                cand_node_in_area=jnp.asarray(dv.cand_node_in_area),
            )
        B = len(names)
        P, C = dv.cand_ok.shape
        A = enc.num_areas
        mesh = self._active_mesh()
        mesh_n = mesh.devices.size if mesh is not None else 1
        if mesh is not None:
            from openr_tpu.ops.fleet_tables import sharded_fleet_tables
            from openr_tpu.parallel.mesh import batch_sharding, replicated

            rep = replicated(mesh)
            dev = {k: jax.device_put(v, rep) for k, v in dev.items()}
            fleet_fn = sharded_fleet_tables(mesh, D, per_area)
            roots_sh = batch_sharding(mesh)
        # pool path (no shard_map needed): root chunks spread round-robin
        # over the pool's HEALTHY chips as committed per-device
        # dispatches — a quarantined chip's share re-packs onto the
        # survivors on the next solve
        pool_devs = None
        chunk_rows = ROOT_CHUNK
        per_dev_args: dict = {}
        if mesh is None and self.pool is not None:
            healthy = self.pool.healthy_indices()
            if len(healthy) > 1:
                pool_devs = healthy
                chunk_rows = min(
                    ROOT_CHUNK, max(32, -(-B // len(healthy)))
                )
        # dense kernel args when the encoding carries the in-edge
        # planes (the scatter-free SPF formulation); also the
        # precondition for the on-device generation delta
        dense_keys = None
        if enc.has_dense:
            with self.probe.phase(pipeline.TRANSFER):
                dev = dict(
                    dev,
                    in_src=jnp.asarray(enc.in_src),
                    in_w=jnp.asarray(enc.in_w),
                    in_ok=jnp.asarray(enc.in_ok),
                    in_rank=jnp.asarray(enc.in_rank),
                    in_has=jnp.asarray(enc.in_has),
                )
                for k in ("src", "dst", "w", "edge_ok"):
                    dev.pop(k)
            dense_keys = True

        def args_on(idx):
            if idx not in per_dev_args:
                d = self.pool.device(idx)
                with self.probe.phase(pipeline.TRANSFER, device=idx):
                    per_dev_args[idx] = {
                        k: jax.device_put(v, d) for k, v in dev.items()
                    }
            return per_dev_args[idx]

        from openr_tpu.decision.backend import STREAM_SLOTS
        from openr_tpu.ops import jit_guard
        from openr_tpu.ops.fleet_tables import (
            fleet_multi_area_tables_dense,
            fleet_multi_area_tables_dense_delta,
        )
        from openr_tpu.ops.route_select import gather_selection_rows

        # on-device generation delta: when the previous generation's
        # chunk outputs are device-resident and every decode input is
        # provably equivalent, each chunk solves with the fused
        # solve+diff kernel and only CHANGED roots' rows cross the host
        # boundary — the unchanged rows patch through from the previous
        # generation's host tables
        delta = self._fleet_delta_ctx(
            enc, dv, table, names, roots_mat, chunk_rows, pool_devs,
            mesh, D,
        )
        if delta is not None:
            use = delta["use"].copy()
            shortest = delta["shortest"].copy()
            lanes = delta["lanes"].copy()
            valid = delta["valid"].copy()
            self.num_delta_solves += 1
        else:
            use = np.empty((B, P, C), bool)
            shortest = np.empty((B, P, A), np.float32)
            lanes = np.empty((B, P, A, D), bool)
            valid = np.empty((B, P, A), bool)

        def dispatch_chunk(off):
            chunk = roots_mat[off : off + chunk_rows]
            with self.probe.phase(pipeline.PAD_PACK):
                b = 1 << max(5, (len(chunk) - 1).bit_length())  # pow2
                b = ((b + mesh_n - 1) // mesh_n) * mesh_n  # whole shards
                padded = np.full((b, A), -1, np.int32)
                padded[: len(chunk)] = chunk
            # a fully -1 pad row would make SPF roots all-absent: fine
            idx = None
            ch = None
            if mesh is not None:
                with self.probe.phase(pipeline.DEVICE_COMPUTE):
                    out = fleet_fn(
                        jax.device_put(padded, roots_sh),
                        dev["src"],
                        dev["dst"],
                        dev["w"],
                        dev["edge_ok"],
                        dev["overloaded"],
                        dev["soft"],
                        dev["cand_area"],
                        dev["cand_node"],
                        dev["cand_ok"],
                        dev["drain_metric"],
                        dev["path_pref"],
                        dev["source_pref"],
                        dev["distance"],
                        dev["cand_node_in_area"],
                    )
            else:
                if pool_devs is not None:
                    idx = pool_devs[(off // chunk_rows) % len(pool_devs)]
                    args = args_on(idx)
                    with self.probe.phase(pipeline.TRANSFER, device=idx):
                        roots_dev = jax.device_put(
                            jnp.asarray(padded), self.pool.device(idx)
                        )
                else:
                    idx = 0
                    args = dev
                    roots_dev = jnp.asarray(padded)
                extra = {}
                if delta is not None:
                    kernel = fleet_multi_area_tables_dense_delta
                    pu, ps, pl, pv = delta["chunks"][off]
                    extra = dict(
                        prev_use=pu,
                        prev_shortest=ps,
                        prev_lanes=pl,
                        prev_valid=pv,
                    )
                elif dense_keys:
                    kernel = fleet_multi_area_tables_dense
                else:
                    kernel = fleet_multi_area_tables
                with self.probe.phase(
                    pipeline.DEVICE_COMPUTE, device=idx
                ), jit_guard.dispatch_device(
                    idx if pool_devs is not None else None
                ):
                    out = call_jit_guarded(
                        kernel,
                        roots=roots_dev,
                        max_degree=D,
                        per_area_distance=per_area,
                        **args,
                        **extra,
                    )
                if delta is not None:
                    out, ch = out[:4], out[4]
                if self.pool is not None and pool_devs is not None:
                    self.pool.note_inflight(idx)
                    self.num_pool_dispatches += 1
                for o in (ch,) if ch is not None else out:
                    o.copy_to_host_async()
            return {
                "off": off,
                "n": len(chunk),
                "idx": idx,
                "out": out,
                "ch": ch,
            }

        def drain_chunk(rec):
            off, n, idx = rec["off"], rec["n"], rec["idx"]
            if idx is not None:
                # streamed completion: the wait charges ONLY this chip
                with self.probe.phase(pipeline.STREAM_DRAIN, device=idx):
                    for o in (
                        (rec["ch"],) if rec["ch"] is not None else rec["out"]
                    ):
                        o.block_until_ready()
                if self.pool is not None and pool_devs is not None:
                    self.pool.note_complete(idx)
            if rec["ch"] is not None:
                with self.probe.phase(pipeline.DEVICE_GET, device=idx):
                    ch = np.asarray(jax.device_get(rec["ch"]))[:n]
                rows = np.nonzero(ch)[0]
                self.num_delta_roots_fetched += len(rows)
                self.num_delta_roots_skipped += n - len(rows)
                if not len(rows):
                    return
                from openr_tpu.decision.backend import ROWSEL_BUCKETS
                from openr_tpu.ops.csr import bucket_for

                k = bucket_for(len(rows), ROWSEL_BUCKETS)
                idx_arr = np.zeros(k, np.int64)
                idx_arr[: len(rows)] = rows
                with self.probe.phase(
                    pipeline.DEVICE_SELECT, device=idx
                ), jit_guard.dispatch_device(
                    idx if pool_devs is not None else None
                ):
                    g = call_jit_guarded(
                        gather_selection_rows,
                        *rec["out"],
                        jnp.asarray(idx_arr),
                    )
                with self.probe.phase(pipeline.DEVICE_GET, device=idx):
                    gu, gs, gl, gv = jax.device_get(g)
                m = len(rows)
                use[off + rows] = gu[:m]
                shortest[off + rows] = gs[:m]
                lanes[off + rows] = gl[:m]
                valid[off + rows] = gv[:m]
                return
            with self.probe.phase(pipeline.DEVICE_GET, device=idx):
                u, s_, l, v = jax.device_get(rec["out"])
            use[off : off + n] = u[:n]
            shortest[off : off + n] = s_[:n]
            lanes[off : off + n] = l[:n]
            valid[off : off + n] = v[:n]

        # streamed dispatch: chunk N+1's pad/transfer overlaps chunk
        # N's solve; the in-flight slot gate keeps any one chip's
        # undrained backlog bounded, and chunks drain in COMPLETION
        # order so host-side assembly overlaps the solves still in
        # flight
        pending: list = []
        chunk_outs: dict = {}
        for off in range(0, B, chunk_rows):
            if pool_devs is not None:
                idx = pool_devs[(off // chunk_rows) % len(pool_devs)]
                while self.pool.inflight(idx) >= STREAM_SLOTS:
                    sel = next(
                        j
                        for j, r in enumerate(pending)
                        if r["idx"] == idx
                    )
                    early = pending.pop(sel)
                    chunk_outs[early["off"]] = early["out"]
                    drain_chunk(early)
            pending.append(dispatch_chunk(off))
        while pending:
            sel = 0
            for j, r in enumerate(pending):
                if r["idx"] is not None and all(
                    o.is_ready()
                    for o in (
                        (r["ch"],) if r["ch"] is not None else r["out"]
                    )
                ):
                    sel = j
                    break
            rec = pending.pop(sel)
            chunk_outs[rec["off"]] = rec["out"]
            drain_chunk(rec)
        self._state = dict(
            enc=enc,
            dv=dv,
            table=table,
            names=names,
            index={n: i for i, n in enumerate(names)},
            use=use,
            shortest=shortest,
            lanes=lanes,
            valid=valid,
        )
        self._retain_fleet_delta(
            enc, dv, table, names, roots_mat, chunk_rows, pool_devs,
            mesh, D, chunk_outs, use, shortest, lanes, valid,
        )
        self._cache_key = key
        self.num_batched_solves += 1
        return self._state

    #: device-resident fleet outputs beyond this size are not retained
    #: as a delta base (mirrors TpuBackend.WARM_MAX_TABLE_BYTES)
    DELTA_MAX_TABLE_BYTES = 64 << 20

    def _fleet_delta_ctx(
        self, enc, dv, table, names, roots_mat, chunk_rows, pool_devs,
        mesh, D,
    ):
        """Eligibility for the fleet generation delta: the previous
        generation's device-resident chunk outputs may vouch for
        'root unchanged' only when every KERNEL INPUT mapping is
        equivalent — same vantage list and per-area root ids, same
        symbol tables (value equality: the fleet engine re-encodes per
        generation), same candidate row->prefix mapping and shapes,
        same chunk decomposition and chip assignment.  Decode inputs
        read fresh state per request (prefix entries, drain lookups,
        min_nexthop), so they impose no additional pinning."""
        prev = self._prev_gen
        if prev is None or mesh is not None or not enc.has_dense:
            return None
        if (
            prev["degree"] != D
            or prev["chunk_rows"] != chunk_rows
            or prev["pool_devs"] != pool_devs
            or prev["names"] != names
            or not np.array_equal(prev["roots_mat"], roots_mat)
            or prev["shape"] != dv.cand_ok.shape
            or prev["row_prefix"] != table.row_prefix
            or prev["id_to_node"]
            != [t.id_to_node for t in enc.topos]
        ):
            return None
        return prev

    def _retain_fleet_delta(
        self, enc, dv, table, names, roots_mat, chunk_rows, pool_devs,
        mesh, D, chunk_outs, use, shortest, lanes, valid,
    ) -> None:
        if mesh is not None or not enc.has_dense or not chunk_outs:
            self._prev_gen = None
            return
        table_bytes = use.nbytes + shortest.nbytes + lanes.nbytes + valid.nbytes
        if table_bytes > self.DELTA_MAX_TABLE_BYTES:
            self._prev_gen = None
            return
        self._prev_gen = dict(
            degree=D,
            chunk_rows=chunk_rows,
            pool_devs=list(pool_devs) if pool_devs is not None else None,
            names=list(names),
            roots_mat=roots_mat,
            shape=dv.cand_ok.shape,
            row_prefix=list(table.row_prefix),
            id_to_node=[t.id_to_node for t in enc.topos],
            chunks=chunk_outs,
            use=use,
            shortest=shortest,
            lanes=lanes,
            valid=valid,
        )

    # -- per-root decode (the backend's own decode path) -------------------

    def compute_for_node(
        self, node: str, area_link_states, prefix_state, change_seq
    ) -> Optional[DecisionRouteDb]:
        """The RouteDb `node` would compute, decoded from the cached
        batch tables; None when node is unknown (caller falls back)."""
        from openr_tpu.decision.backend import TpuBackend

        from openr_tpu.tracing import pipeline

        st = self._tables_for(area_link_states, prefix_state, change_seq)
        ri = st["index"].get(node)
        if ri is None:
            return None
        self.num_decodes += 1
        tb = TpuBackend(self._vantage_solver(node))
        table = st["table"]
        with self.probe.phase(pipeline.DECODE):
            row_items = [
                (int(r), table.row_prefix[r])
                for r in np.nonzero(st["use"][ri].any(axis=1))[0]
                if table.row_prefix[r] is not None
            ]
            results = tb._decode_rows(
                row_items,
                st["use"][ri],
                st["shortest"][ri],
                st["lanes"][ri],
                st["valid"][ri],
                st["dv"],
                None,
                st["enc"],
                area_link_states,
                prefix_state,
            )
            db = DecisionRouteDb()
            for _prefix, entry in sorted(results.items()):
                if entry is not None:
                    db.add_unicast_route(entry)
            if self.solver.enable_node_segment_label:
                tb.solver._build_node_label_routes(area_link_states, db)
        return db

    def _vantage_solver(self, node: str) -> SpfSolver:
        s = self.solver
        return SpfSolver(
            node,
            enable_v4=s.enable_v4,
            enable_node_segment_label=s.enable_node_segment_label,
            enable_best_route_selection=s.enable_best_route_selection,
            v4_over_v6_nexthop=s.v4_over_v6_nexthop,
            route_selection_algorithm=s.route_selection_algorithm,
        )

    # -- fleet summary -----------------------------------------------------

    def fleet_summary(
        self, area_link_states, prefix_state, change_seq
    ) -> Dict[str, dict]:
        """Per-node unicast route counts + total nexthops from ONE batch
        solve — the 'what does every router see' operator view.  Applies
        the same host-side gates the decode applies (v4 family,
        skip-if-self, min-nexthop over the cross-area merge) so counts
        always match compute_for_node."""
        st = self._tables_for(area_link_states, prefix_state, change_seq)
        dv, table = st["dv"], st["table"]
        use, shortest, lanes, valid = (
            st["use"],
            st["shortest"],
            st["lanes"],
            st["valid"],
        )
        B, P, A = valid.shape

        include = np.asarray(
            [
                p is not None
                and (
                    self.solver.enable_v4
                    or self.solver.v4_over_v6_nexthop
                    or not prefix_is_v4(p)
                )
                for p in table.row_prefix
            ],
            bool,
        )  # [P]
        # cross-area min-metric merge, vectorized (SpfSolver.cpp:276-302)
        m = np.where(valid, shortest, np.inf)  # [B, P, A]
        m_star = m.min(axis=2)  # [B, P]
        at_min = valid & (m == m_star[:, :, None])
        num_nh_area = lanes.sum(axis=3)  # [B, P, A]
        merged = (num_nh_area * at_min).sum(axis=2)  # [B, P]
        # per-root gates, matching the backend decode exactly:
        #   min-nexthop req = max over THIS root's selection winners
        #   (not all candidates — a losing advertiser's requirement must
        #   not gate the winner's route)
        #   skip-if-self by GLOBAL candidate identity (adv_gid interned
        #   per advertiser name; a never-advertising root has no gid and
        #   can never self-win)
        adv_gid = table.adv_gid  # [P, C] (-1 = empty slot)
        gid_of = table._node_gid
        self_win = np.zeros((B, P), bool)
        req = np.zeros((B, P), np.int32)
        for i, name in enumerate(st["names"]):
            req[i] = np.max(np.where(use[i], dv.min_nexthop, 0), axis=1)
            g = gid_of.get(name)
            if g is not None:
                self_win[i] = (use[i] & (adv_gid == g)).any(axis=1)
        route_ok = (
            include[None, :]
            & valid.any(axis=2)
            & ~self_win
            & (merged > 0)
            & (merged >= req)
        )
        out = {}
        for i, name in enumerate(st["names"]):
            out[name] = {
                "num_routes": int(route_ok[i].sum()),
                "total_nexthops": int(merged[i][route_ok[i]].sum()),
            }
        return out
