"""Fleet RIB engine: every node's what-if RouteDb from one device batch.

The ctrl API's getRouteDbComputed answers "what routes would node X
compute?" — the reference runs a fresh scalar SpfSolver pass per call
(Decision.cpp:342), so a fleet-wide sweep costs |V| sequential
Dijkstras.  Here all |V| vantage points are one batched device solve
(ops/allroots.py: root = a batch dim of the fused SPF+selection
kernel); the tables are cached until the LSDB changes, and each ctrl
request decodes ONLY its root.

Eligibility (else the scalar path runs, exactness preserved): a single
area, SHORTEST_DISTANCE with best-route selection, and no KSP2_ED_ECMP
advertisements (the k-path trace is per-root host work the batch can't
amortize yet)."""

from __future__ import annotations

from typing import Dict, Optional

from openr_tpu.decision.rib import DecisionRouteDb, RibUnicastEntry
from openr_tpu.decision.spf_solver import (
    SpfSolver,
    drained_entry,
    select_best_node_area,
)
from openr_tpu.types import (
    NextHop,
    PrefixForwardingAlgorithm,
    RouteComputationRules,
    prefix_is_v4,
)


class FleetRibEngine:
    """Caches all-roots selection tables per LSDB change generation."""

    def __init__(self, solver: SpfSolver) -> None:
        self.solver = solver  # settings template (v4 flags, labels, algo)
        self._cache_key = None
        self._tables = None
        self._topo = None
        self._cands = None
        self._all_entries = None
        self._ksp2_scan = None  # (change_seq, result)
        self.num_batched_solves = 0
        self.num_decodes = 0

    # -- eligibility -------------------------------------------------------

    def eligible(self, area_link_states, prefix_state, change_seq) -> bool:
        if len(area_link_states) != 1:
            return False
        s = self.solver
        if (
            not s.enable_best_route_selection
            or s.route_selection_algorithm
            != RouteComputationRules.SHORTEST_DISTANCE
        ):
            return False
        # the O(P*C) KSP2 scan is cached on the same change generation
        # as the tables — ctrl requests between LSDB changes skip it
        if self._ksp2_scan is not None and self._ksp2_scan[0] == change_seq:
            return self._ksp2_scan[1]
        ok = not any(
            entry.forwarding_algorithm
            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
            for entries in prefix_state.prefixes().values()
            for entry in entries.values()
        )
        self._ksp2_scan = (change_seq, ok)
        return ok

    # -- table computation (cached) ---------------------------------------

    def _tables_for(self, area_link_states, prefix_state, change_seq):
        from openr_tpu.ops.allroots import AllRootsRouteCompute
        from openr_tpu.ops.csr import encode_link_state, encode_prefix_candidates

        (area, ls), = area_link_states.items()
        key = (area, ls.topology_seq, change_seq)
        if self._cache_key == key and self._tables is not None:
            return self._tables, self._topo, area
        topo = encode_link_state(ls)
        cands = encode_prefix_candidates(prefix_state, topo, area)
        compute = AllRootsRouteCompute(topo, cands, prefixes=cands.prefixes)
        import numpy as np

        roots = np.arange(topo.num_nodes, dtype=np.int32)
        self._tables = compute.run(roots)
        self._topo = topo
        self._cands = cands
        self._all_entries = prefix_state.prefixes()
        self._cache_key = key
        self.num_batched_solves += 1
        return self._tables, self._topo, area

    # -- per-root decode ---------------------------------------------------

    def compute_for_node(
        self, node: str, area_link_states, prefix_state, change_seq
    ) -> Optional[DecisionRouteDb]:
        """The RouteDb `node` would compute, decoded from the cached
        batch tables; None when node is unknown (caller falls back)."""
        tables, topo, area = self._tables_for(
            area_link_states, prefix_state, change_seq
        )
        if node not in topo.node_ids:
            return None
        self.num_decodes += 1
        ri = tables.root_index(topo.node_id(node))
        # the requested node's view uses ITS solver settings shape: same
        # config as the local solver, different vantage (Decision.cpp:342)
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop
        out_edges = topo.root_out_edges(node)
        all_entries = self._all_entries
        cand_node = self._cands.cand_node
        import numpy as np

        db = DecisionRouteDb()
        valid_rows = np.nonzero(tables.valid[ri])[0]
        use_ri = tables.use[ri]
        lanes_ri = tables.lanes[ri]
        for p in valid_rows:
            prefix = tables.prefixes[p]
            if prefix_is_v4(prefix) and not v4_ok:
                continue
            entries = all_entries.get(prefix)
            if not entries:
                continue
            # selection winners: candidate c of prefix p → (node, area)
            wset = {
                (topo.id_to_node[int(cand_node[p, c])], area)
                for c in np.nonzero(use_ri[p])[0]
            }
            if not wset:
                continue
            m = float(tables.metric[ri, p])
            nhs = set()
            for lane in np.nonzero(lanes_ri[p])[0]:
                if lane >= len(out_edges):
                    continue
                link, neighbor = out_edges[lane]
                nhs.add(
                    NextHop(
                        address=(
                            link.get_nh_v4_from_node(node)
                            if prefix_is_v4(prefix)
                            and not self.solver.v4_over_v6_nexthop
                            else link.get_nh_v6_from_node(node)
                        ),
                        if_name=link.get_iface_from_node(node),
                        metric=int(m),
                        area=link.area,
                        neighbor_node_name=neighbor,
                    )
                )
            if not nhs:
                continue
            best_node_area = select_best_node_area(wset, node)
            best = entries.get(best_node_area)
            if best is None:
                continue
            if SpfSolver._is_node_drained(best_node_area, area_link_states):
                best = drained_entry(best)
            db.add_unicast_route(
                RibUnicastEntry(
                    prefix=prefix,
                    nexthops=nhs,
                    best_prefix_entry=best,
                    best_area=best_node_area[1],
                    igp_cost=m,
                    local_prefix_considered=any(
                        n == node for (n, _a) in entries.keys()
                    ),
                )
            )
        if self.solver.enable_node_segment_label:
            # label routes are O(V) scalar per request, vantage-specific
            s = self._vantage_solver(node)
            s._build_node_label_routes(area_link_states, db)
        return db

    def _vantage_solver(self, node: str) -> SpfSolver:
        s = self.solver
        return SpfSolver(
            node,
            enable_v4=s.enable_v4,
            enable_node_segment_label=s.enable_node_segment_label,
            enable_best_route_selection=s.enable_best_route_selection,
            v4_over_v6_nexthop=s.v4_over_v6_nexthop,
            route_selection_algorithm=s.route_selection_algorithm,
        )

    # -- fleet summary -----------------------------------------------------

    def fleet_summary(
        self, area_link_states, prefix_state, change_seq
    ) -> Dict[str, dict]:
        """Per-node route counts + total nexthops from ONE batch solve —
        the 'what does every router see' operator view."""
        import numpy as np

        tables, topo, _area = self._tables_for(
            area_link_states, prefix_state, change_seq
        )
        # same per-prefix family gate compute_for_node applies — counts
        # must agree with the decoded RouteDbs
        v4_ok = self.solver.enable_v4 or self.solver.v4_over_v6_nexthop
        include = np.asarray(
            [v4_ok or not prefix_is_v4(p) for p in tables.prefixes], bool
        )
        out = {}
        for i, rid in enumerate(tables.roots):
            name = topo.id_to_node[int(rid)]
            counted = tables.valid[i] & include
            out[name] = {
                "num_routes": int(counted.sum()),
                "total_nexthops": int(tables.num_nh[i][counted].sum()),
            }
        return out
