"""RIB entry types and route-update deltas
(reference: openr/decision/RibEntry.h, RouteUpdate.h).

`DecisionRouteDb` is the full computed RIB; `DecisionRouteUpdate` is the
delta container pushed Decision → Fib → PrefixManager with FULL_SYNC or
INCREMENTAL semantics (RouteUpdate.h:30-80).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from openr_tpu.types import (
    MplsRoute,
    NextHop,
    PerfEvents,
    PrefixEntry,
    RouteDatabase,
    RouteDatabaseDelta,
    TraceContext,
    UnicastRoute,
)


@dataclass
class RibUnicastEntry:
    """One computed unicast route (RibEntry.h:60-140)."""

    prefix: str
    #: SHARED-OWNERSHIP INVARIANT: the backend memoizes nexthop sets and
    #: hands the SAME (frozen) set to many entries, and
    #: best_prefix_entry aliases the live PrefixState entry.  Never
    #: mutate either in place — reassign (as RibPolicy does).
    nexthops: Set[NextHop] = field(default_factory=set)
    best_prefix_entry: PrefixEntry = field(default_factory=lambda: PrefixEntry("::/0"))
    best_area: str = ""
    do_not_install: bool = False
    igp_cost: float = 0
    #: was the local node's own advertisement part of best-path selection
    local_prefix_considered: bool = False

    def to_unicast_route(self) -> UnicastRoute:
        return UnicastRoute(dest=self.prefix, next_hops=sorted_nexthops(self.nexthops))

    def eq_ignoring_cost(self, other: "RibUnicastEntry") -> bool:
        """Reference equality (RibEntry.h:82-87): igp_cost and best_area are
        deliberately EXCLUDED so remote metric shifts that leave nexthops
        unchanged do not churn the FIB."""
        return (
            self.prefix == other.prefix
            and self.nexthops == other.nexthops
            and self.best_prefix_entry == other.best_prefix_entry
            and self.do_not_install == other.do_not_install
            and self.local_prefix_considered == other.local_prefix_considered
        )


@dataclass
class RibMplsEntry:
    """One computed MPLS label route (RibEntry.h:150-198)."""

    label: int
    nexthops: Set[NextHop] = field(default_factory=set)

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(top_label=self.label, next_hops=sorted_nexthops(self.nexthops))


def sorted_nexthops(nhs) -> List[NextHop]:
    return sorted(
        nhs,
        key=lambda nh: (nh.area, nh.neighbor_node_name, nh.if_name, nh.address),
    )


def _nexthop_summary(nh: NextHop):
    return (
        nh.neighbor_node_name,
        nh.if_name,
        nh.address,
        nh.metric,
        nh.weight,
        nh.area,
        None
        if nh.mpls_action is None
        else (
            nh.mpls_action.action,
            nh.mpls_action.swap_label,
            nh.mpls_action.push_labels,
        ),
    )


def route_db_summary(db):
    """Canonical comparable view of a full RouteDb — unicast AND MPLS
    routes with every field that affects forwarding (nexthop addresses,
    metrics, weights, label actions, igp cost, best area).  Differential
    tests and the parity benches compare THIS, so a device-path
    regression in any dimension fails loudly."""
    if db is None:
        return None
    return {
        "unicast": {
            p: (
                round(e.igp_cost, 3),
                e.best_area,
                e.best_prefix_entry.metrics.drain_metric
                if e.best_prefix_entry is not None
                else None,
                sorted(_nexthop_summary(nh) for nh in e.nexthops),
            )
            for p, e in db.unicast_routes.items()
        },
        "mpls": {
            label: sorted(_nexthop_summary(nh) for nh in e.nexthops)
            for label, e in db.mpls_routes.items()
        },
    }


@dataclass
class DecisionRouteDb:
    """Full RIB keyed by prefix / label (RouteUpdate.h DecisionRouteDb)."""

    unicast_routes: Dict[str, RibUnicastEntry] = field(default_factory=dict)
    mpls_routes: Dict[int, RibMplsEntry] = field(default_factory=dict)

    def add_unicast_route(self, entry: RibUnicastEntry) -> None:
        self.unicast_routes[entry.prefix] = entry

    def add_mpls_route(self, entry: RibMplsEntry) -> None:
        self.mpls_routes[entry.label] = entry

    def calculate_update(self, new_db: "DecisionRouteDb") -> "DecisionRouteUpdate":
        """Diff self → new_db (reference DecisionRouteDb::calculateUpdate)."""
        update = DecisionRouteUpdate(type=DecisionRouteUpdateType.INCREMENTAL)
        for prefix, entry in new_db.unicast_routes.items():
            old = self.unicast_routes.get(prefix)
            if old is None or not old.eq_ignoring_cost(entry):
                update.unicast_routes_to_update[prefix] = entry
        for prefix in self.unicast_routes:
            if prefix not in new_db.unicast_routes:
                update.unicast_routes_to_delete.append(prefix)
        for label, mentry in new_db.mpls_routes.items():
            old_m = self.mpls_routes.get(label)
            if old_m is None or old_m != mentry:
                update.mpls_routes_to_update[label] = mentry
        for label in self.mpls_routes:
            if label not in new_db.mpls_routes:
                update.mpls_routes_to_delete.append(label)
        return update

    def calculate_update_for(
        self, new_db: "DecisionRouteDb", prefixes
    ) -> "DecisionRouteUpdate":
        """Diff self → new_db restricted to ``prefixes`` — O(changed), not
        O(total).  Valid when the caller guarantees every other unicast
        route is unchanged (the incremental-rebuild contract: backends
        patch only the changed prefixes, Decision.cpp:908-952).  MPLS
        routes are diffed in full (O(labels) = O(nodes), cheap relative
        to the prefix table)."""
        update = DecisionRouteUpdate(type=DecisionRouteUpdateType.INCREMENTAL)
        for prefix in prefixes:
            old = self.unicast_routes.get(prefix)
            new = new_db.unicast_routes.get(prefix)
            if new is None:
                if old is not None:
                    update.unicast_routes_to_delete.append(prefix)
            elif old is None or not old.eq_ignoring_cost(new):
                update.unicast_routes_to_update[prefix] = new
        for label, mentry in new_db.mpls_routes.items():
            old_m = self.mpls_routes.get(label)
            if old_m is None or old_m != mentry:
                update.mpls_routes_to_update[label] = mentry
        for label in self.mpls_routes:
            if label not in new_db.mpls_routes:
                update.mpls_routes_to_delete.append(label)
        return update

    def to_route_database(self, node_name: str = "") -> RouteDatabase:
        return RouteDatabase(
            this_node_name=node_name,
            unicast_routes=[
                e.to_unicast_route() for e in self.unicast_routes.values()
            ],
            mpls_routes=[e.to_mpls_route() for e in self.mpls_routes.values()],
        )


class DecisionRouteUpdateType(enum.IntEnum):
    FULL_SYNC = 0
    INCREMENTAL = 1


@dataclass
class DecisionRouteUpdate:
    """Delta pushed on routeUpdatesQueue (RouteUpdate.h:30-184)."""

    type: DecisionRouteUpdateType = DecisionRouteUpdateType.INCREMENTAL
    unicast_routes_to_update: Dict[str, RibUnicastEntry] = field(default_factory=dict)
    unicast_routes_to_delete: List[str] = field(default_factory=list)
    mpls_routes_to_update: Dict[int, RibMplsEntry] = field(default_factory=dict)
    mpls_routes_to_delete: List[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None
    #: causal-trace handle from the Decision rebuild that produced this
    #: delta; Fib parents its programming span here and closes the trace
    trace_ctx: Optional["TraceContext"] = None
    #: fast-reroute provenance: True when this delta is a precomputed
    #: protection patch published ahead of the confirming warm solve.
    #: ``frr_generation`` is the Decision change_seq the patch was
    #: applied AT — the streaming tier and Fib stamp it so monotone
    #: generation ordering holds across the patch and its confirm
    frr: bool = False
    frr_generation: int = 0

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )

    def size(self) -> int:
        return (
            len(self.unicast_routes_to_update)
            + len(self.unicast_routes_to_delete)
            + len(self.mpls_routes_to_update)
            + len(self.mpls_routes_to_delete)
        )

    def to_route_database_delta(self) -> RouteDatabaseDelta:
        return RouteDatabaseDelta(
            unicast_routes_to_update=[
                e.to_unicast_route() for e in self.unicast_routes_to_update.values()
            ],
            unicast_routes_to_delete=list(self.unicast_routes_to_delete),
            mpls_routes_to_update=[
                e.to_mpls_route() for e in self.mpls_routes_to_update.values()
            ],
            mpls_routes_to_delete=list(self.mpls_routes_to_delete),
            perf_events=self.perf_events,
        )
