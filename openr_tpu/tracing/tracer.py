"""Tracer — causal spans across the Spark→KvStore→Decision→Fib pipeline.

The reference answers "where did the convergence time go?" with
PerfEvents breadcrumbs (Types.thrift:80-96) and fb303 counters; DeltaPath
(PAPERS.md) argues per-update dataflow latency is *the* metric an
incremental routing engine must expose.  This module is the generalized
form: every stage records a `Span` (start/end on the injected `Clock`)
linked by a `TraceContext` that rides queue items and KvStore flooding
metadata, so one link flap yields a multi-node span tree from the Spark
FSM transition to the Fib programming ack — inspectable via the ctrl API
(`get_traces`), `breeze monitor trace`, or a Perfetto export.

Design constraints:
  * deterministic: trace ids are derived from the minting event's content
    (node, event, virtual time, attrs) and span ids from a per-trace
    sequence — never from a node-global mint counter, whose value would
    depend on how concurrent traces interleave.  Ids ride TraceContext
    into flooded KvStore values, so they must replay identically under
    ANY fiber schedule, not just the canonical one (the chaos
    schedule-perturbation sweep enforces this byte-for-byte);
  * bounded: completed spans live in a fixed ring (evictions counted),
    spans opened but never closed are evicted past a cap and counted as
    `trace.dropped_spans` (the chaos invariant: drops stay bounded);
  * free when off: with `enabled=False` every entry point returns a
    shared no-op in O(1) with no allocation — the hot path pays one
    attribute check.
"""

from __future__ import annotations

import json
import zlib
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional

from openr_tpu.common.runtime import Clock, CounterMap
from openr_tpu.types import TraceContext


class Span:
    """One timed stage of a trace.  `end_ms` is None while open."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id",
        "node", "module", "start_ms", "end_ms", "attrs",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str,
        parent_id: str,
        node: str,
        module: str,
        start_ms: float,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.node = node
        self.module = module
        self.start_ms = start_ms
        self.end_ms: Optional[float] = None
        self.attrs: Dict[str, Any] = attrs or {}

    def duration_ms(self) -> Optional[float]:
        if self.end_ms is None:
            return None
        return self.end_ms - self.start_ms

    def to_wire(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "node": self.node,
            "module": self.module,
            "start_ms": self.start_ms,
            "end_ms": self.end_ms,
            "duration_ms": self.duration_ms(),
            "attrs": dict(self.attrs),
        }


class _NoopSpan:
    """Shared sentinel returned by a disabled Tracer: accepts the same
    surface as Span but records nothing."""

    __slots__ = ()
    name = trace_id = span_id = parent_id = node = module = ""
    start_ms = 0.0
    end_ms: Optional[float] = None
    attrs: Dict[str, Any] = {}

    @staticmethod
    def duration_ms() -> Optional[float]:
        return None

    @staticmethod
    def to_wire() -> Dict[str, Any]:
        return {}


NOOP_SPAN = _NoopSpan()


class _SpanScope:
    """Context manager from Tracer.span()."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span) -> None:
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None and self.span is not NOOP_SPAN:
            self.span.attrs["error"] = exc_type.__name__
        self._tracer.end_span(self.span)


class Tracer:
    """Per-node span recorder.  All timing goes through the injected
    Clock; all ids are content-derived (hash of event + virtual time +
    attrs, with per-trace span counters), so two runs that record the
    same spans mint the same ids regardless of interleaving."""

    def __init__(
        self,
        node_name: str,
        clock: Optional[Clock] = None,
        counters: Optional[CounterMap] = None,
        enabled: bool = True,
        max_spans: int = 4096,
        max_open_spans: int = 512,
    ) -> None:
        if enabled and clock is None:
            raise ValueError("an enabled Tracer needs an injected Clock")
        self.node_name = node_name
        self.clock = clock
        self.counters = counters
        self.enabled = enabled
        self.max_spans = max_spans
        self.max_open_spans = max_open_spans
        self._done: Deque[Span] = deque()
        self._open: "OrderedDict[str, Span]" = OrderedDict()
        #: per-trace span counters (LRU-bounded): span ids must NOT come
        #: from a node-global sequence — concurrent traces interleave
        #: their allocations there, so the ids (which ride TraceContext
        #: into flooded kvstore values) would depend on fiber dispatch
        #: order.  A per-trace counter follows only the trace's own
        #: causal chain, which replays identically under any schedule.
        self._span_seq: "OrderedDict[str, int]" = OrderedDict()
        #: minted trace ids (LRU-bounded) for collision disambiguation
        self._minted: "OrderedDict[str, int]" = OrderedDict()
        self.num_completed = 0
        #: open spans evicted unfinished — the leak/overload signal the
        #: chaos invariant bounds
        self.num_dropped = 0
        #: completed spans that fell off the ring (normal steady-state
        #: turnover on a long-lived daemon)
        self.num_evicted = 0

    # -- mint / record -----------------------------------------------------

    def _mint_trace_id(self, event: str, attrs: Dict[str, Any]) -> str:
        """Trace identity = the minting event's content, so a trace gets
        the same id on every legal schedule (and on every shard of a
        replayed run).  Distinct same-content events at the same virtual
        instant are indistinguishable, so the collision suffix is
        order-free too."""
        blob = json.dumps(
            [event, self.clock.now_ms(), attrs], sort_keys=True, default=repr
        )
        tid = f"{self.node_name}:{zlib.crc32(blob.encode()):08x}"
        n = self._minted.get(tid, 0) + 1
        self._minted[tid] = n
        self._minted.move_to_end(tid)
        while len(self._minted) > self.max_spans:
            self._minted.popitem(last=False)
        return tid if n == 1 else f"{tid}.{n}"

    def _next_span_id(self, trace_id: str) -> str:
        n = self._span_seq.get(trace_id, 0) + 1
        self._span_seq[trace_id] = n
        self._span_seq.move_to_end(trace_id)
        while len(self._span_seq) > self.max_spans:
            self._span_seq.popitem(last=False)
        return f"{trace_id}.{self.node_name}.{n}"

    def start_trace(
        self, event: str, module: str = "", **attrs: Any
    ) -> Optional[TraceContext]:
        """Mint a new trace at an event origin.  Records the origin as an
        instant root span and returns the propagation handle (None when
        tracing is disabled — callers pass it through unchanged)."""
        if not self.enabled:
            return None
        now = self.clock.now() * 1000.0
        sid = self._mint_trace_id(event, attrs)
        span = Span(event, sid, sid, "", self.node_name, module, now, attrs)
        span.end_ms = now
        self._finish(span)
        return TraceContext(
            trace_id=sid,
            span_id=sid,
            origin_node=self.node_name,
            origin_event=event,
            t0_ms=self.clock.now_ms(),
        )

    def start_span(
        self,
        name: str,
        ctx: Optional[TraceContext] = None,
        module: str = "",
        **attrs: Any,
    ):
        """Open a span under `ctx` (fresh trace when ctx is None)."""
        if not self.enabled:
            return NOOP_SPAN
        if ctx is not None:
            trace_id = ctx.trace_id
            sid = self._next_span_id(trace_id)
            parent = ctx.span_id
        else:
            sid = trace_id = self._mint_trace_id(name, attrs)
            parent = ""
        span = Span(
            name, trace_id, sid, parent, self.node_name, module,
            self.clock.now() * 1000.0, attrs,
        )
        self._open[sid] = span
        while len(self._open) > self.max_open_spans:
            _, leaked = self._open.popitem(last=False)
            leaked.attrs["dropped"] = True
            # seal it: a late end_span on a dropped span is a no-op, and
            # the span never reaches the completed ring
            leaked.end_ms = leaked.start_ms
            self.num_dropped += 1
            if self.counters is not None:
                self.counters.bump("trace.dropped_spans")
        return span

    def end_span(self, span, **attrs: Any) -> None:
        if span is NOOP_SPAN or not isinstance(span, Span):
            return
        if span.end_ms is not None:
            return  # already closed (or dropped from the open table)
        if attrs:
            span.attrs.update(attrs)
        span.end_ms = self.clock.now() * 1000.0
        self._open.pop(span.span_id, None)
        self._finish(span)

    def instant(
        self,
        name: str,
        ctx: Optional[TraceContext] = None,
        module: str = "",
        **attrs: Any,
    ):
        """Zero-duration span (event marker)."""
        if not self.enabled:
            return NOOP_SPAN
        span = self.start_span(name, ctx, module, **attrs)
        self.end_span(span)
        return span

    def span(self, name: str, ctx=None, module: str = "", **attrs: Any):
        """`with tracer.span("decision.rebuild", ctx) as sp:` scope."""
        return _SpanScope(self, self.start_span(name, ctx, module, **attrs))

    def child_ctx(
        self, span, ctx: Optional[TraceContext] = None
    ) -> Optional[TraceContext]:
        """Propagation handle re-based onto `span` so the next stage's
        span parents here; origin fields (node/event/t0) stay pinned to
        the minting event."""
        if span is NOOP_SPAN or not isinstance(span, Span):
            return ctx
        if ctx is not None:
            return TraceContext(
                trace_id=ctx.trace_id,
                span_id=span.span_id,
                origin_node=ctx.origin_node,
                origin_event=ctx.origin_event,
                t0_ms=ctx.t0_ms,
            )
        return TraceContext(
            trace_id=span.trace_id,
            span_id=span.span_id,
            origin_node=self.node_name,
            origin_event=span.name,
            t0_ms=int(span.start_ms),
        )

    def observe(self, key: str, value: float) -> None:
        """Histogram passthrough (None-safe) for stages that only hold a
        tracer reference (jit_guard's kernel spans)."""
        if self.counters is not None:
            self.counters.observe(key, value)

    def _finish(self, span: Span) -> None:
        self._done.append(span)
        self.num_completed += 1
        while len(self._done) > self.max_spans:
            self._done.popleft()
            self.num_evicted += 1
            if self.counters is not None:
                self.counters.bump("trace.spans_evicted")

    # -- query surface (ctrl API get_traces) -------------------------------

    def get_spans(self, trace_id: Optional[str] = None) -> List[Span]:
        """Completed spans, oldest first; optionally one trace only."""
        if trace_id is None:
            return list(self._done)
        return [s for s in self._done if s.trace_id == trace_id]

    def trace_ids(self) -> List[str]:
        """Distinct trace ids present in the ring, oldest first."""
        seen: "OrderedDict[str, None]" = OrderedDict()
        for s in self._done:
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def stats(self) -> Dict[str, float]:
        """Gauge provider for Monitor.add_counter_provider."""
        return {
            "trace.enabled": 1.0 if self.enabled else 0.0,
            "trace.spans_completed": float(self.num_completed),
            "trace.dropped_spans": float(self.num_dropped),
            "trace.spans_evicted": float(self.num_evicted),
            "trace.open_spans": float(len(self._open)),
        }


_DISABLED = Tracer("-", clock=None, enabled=False)


def disabled_tracer() -> Tracer:
    """Shared always-off tracer: the default for modules constructed
    without one, so call sites never need a None check."""
    return _DISABLED
