"""Flight recorder — a bounded post-mortem ring that dumps itself.

When an InvariantChecker breach, a chip quarantine, or a watchdog crash
fires, the evidence an operator needs (the spans leading up to it, the
counter movement, the queue watermarks) is usually GONE by the time a
human attaches — rings rolled over, counters kept counting.  The
recorder keeps a small per-node window of that evidence and, on a
trigger, freezes it into one self-contained artifact:

  * a Chrome-trace event list of the most recent completed spans (the
    quarantine span tree for a lying chip is in here — `resilience.*`
    spans carry the ``device`` attr, so Perfetto shows the chip lane);
  * a `MetricsSnapshot` (counters + histogram buckets), with
    wall-clock-dependent ``process.*`` gauges EXCLUDED so two seeded
    replays of the same chaos plan produce byte-identical dumps — the
    property that turns a post-mortem into a diffable regression
    artifact (chaos tests assert it);
  * the frame ring: periodic counter DELTAS + queue watermarks
    (`record_frame` — the Watchdog calls it each sweep, so the dump
    shows the few minutes of movement before the event, not just the
    terminal totals).

Dump targets: always in-memory (``dumps`` list + ``last_dump`` bytes,
the ctrl/chaos-test surface); optionally a directory
(``tracing_config.flight_recorder_dir``) where each dump lands as
``flight_<node>_<seq>_<reason>.json`` — the seq is a deterministic
counter, never a wall timestamp.
"""

from __future__ import annotations

import json
import re
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

from openr_tpu.monitor.metrics import (
    NONDETERMINISTIC_PREFIXES,
    MetricsSnapshot,
)
from openr_tpu.tracing.export import chrome_trace_events

_REASON_SAFE = re.compile(r"[^a-zA-Z0-9_.-]+")

#: span attrs that reflect PROCESS-LOCAL jit-cache state (did this
#: dispatch pay an XLA compile / a guard heal), not protocol state — a
#: seeded replay in a warm process would legitimately differ on them,
#: so dumps drop them to keep the byte-identical replay contract; the
#: live trace surfaces (`get_traces`, Chrome export) keep them
VOLATILE_SPAN_ATTRS = ("compiled", "healed")


class FlightRecorder:
    def __init__(
        self,
        node_name: str,
        clock,
        tracer,
        counters,
        max_spans: int = 512,
        max_frames: int = 256,
        max_dumps: int = 8,
        out_dir: str = "",
        queue_stats_fn: Optional[Callable[[], Dict[str, float]]] = None,
        generation_fn: Optional[Callable[[], Any]] = None,
        trigger_min_interval_ms: int = 250,
    ) -> None:
        self.node_name = node_name
        self.clock = clock
        self.tracer = tracer
        self.counters = counters
        self.max_spans = max_spans
        self.out_dir = out_dir
        self._queue_stats = queue_stats_fn
        self._generation = generation_fn
        self._frames: Deque[Dict[str, Any]] = deque(maxlen=max_frames)
        self._last_counters: Dict[str, float] = {}
        self.dumps: Deque[bytes] = deque(maxlen=max_dumps)
        self.last_dump: Optional[bytes] = None
        self.last_reason: str = ""
        self.num_dumps = 0
        self._seq = 0
        #: TRIGGERED dumps (the on_* hooks) landing within this window
        #: of the previous one are coalesced: several listeners firing
        #: in one Monitor sweep (a quarantine tripping an invariant
        #: breach) describe ONE incident window — dumping it twice
        #: doubles the ring churn and buys nothing.  Explicit dump()
        #: calls (ctrl/operator/chaos harness) are never suppressed.
        self.trigger_min_interval_ms = trigger_min_interval_ms
        self._last_trigger_ms: Optional[int] = None
        self.num_suppressed = 0
        #: reasons coalesced into the previous dump since it fired
        self.suppressed_reasons: List[str] = []

    # -- the rolling window ------------------------------------------------

    def record_frame(self, label: str = "") -> None:
        """Append one frame: counter deltas since the previous frame +
        current queue watermarks.  Cheap enough for every watchdog
        sweep; deterministic under SimClock."""
        now = dict(self.counters.dump())
        deltas = {
            k: v - self._last_counters.get(k, 0.0)
            for k, v in now.items()
            if v != self._last_counters.get(k, 0.0)
            and not k.startswith(NONDETERMINISTIC_PREFIXES)
        }
        self._last_counters = now
        frame: Dict[str, Any] = {
            "ts_ms": int(self.clock.now_ms()),
            "label": label,
            "counter_deltas": dict(sorted(deltas.items())),
        }
        if self._queue_stats is not None:
            frame["queue_watermarks"] = dict(
                sorted(self._queue_stats().items())
            )
        self._frames.append(frame)

    # -- trigger hooks (wired in main.py) ----------------------------------

    def on_quarantine(self, info: Dict[str, Any]) -> None:
        """BackendHealthGovernor quarantine listener."""
        device = info.get("device")
        tag = f"dev{device}" if device is not None else "backend"
        self.trigger_dump(f"quarantine_{tag}", extra=info)

    def on_watchdog_crash(self, reason: str) -> None:
        self.trigger_dump("watchdog_crash", extra={"crash_reason": reason})

    def on_invariant_breach(self, violation: str) -> None:
        self.trigger_dump("invariant_breach", extra={"violation": violation})

    def trigger_dump(
        self, reason: str, extra: Optional[Dict[str, Any]] = None
    ) -> Optional[bytes]:
        """Rate-limited/deduped dump for automatic triggers: when a
        second trigger lands within ``trigger_min_interval_ms`` of the
        previous one (same Monitor sweep, same incident window), it is
        coalesced — counted, its reason recorded — instead of dumped
        again.  Returns the dump bytes, or None when coalesced."""
        now_ms = int(self.clock.now_ms())
        if (
            self._last_trigger_ms is not None
            and now_ms - self._last_trigger_ms < self.trigger_min_interval_ms
        ):
            self.num_suppressed += 1
            self.suppressed_reasons.append(reason)
            self.counters.bump("trace.flight_dumps_suppressed")
            return None
        self._last_trigger_ms = now_ms
        self.suppressed_reasons = []
        return self.dump(reason, extra=extra)

    # -- the dump ----------------------------------------------------------

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None) -> bytes:
        """Freeze the window into one self-contained JSON artifact and
        return its (deterministic) bytes."""
        self.record_frame(label=f"dump:{reason}")
        spans = []
        for s in self.tracer.get_spans()[-self.max_spans:]:
            wire = s.to_wire()
            for attr in VOLATILE_SPAN_ATTRS:
                wire.get("attrs", {}).pop(attr, None)
            spans.append(wire)
        snapshot = MetricsSnapshot.capture(
            counters=self.counters,
            node_name=self.node_name,
            clock=self.clock,
            generation=(
                self._generation() if self._generation is not None else None
            ),
            exclude=NONDETERMINISTIC_PREFIXES,
        )
        doc = {
            "kind": "openr_tpu_flight_recorder_dump",
            "node": self.node_name,
            "reason": reason,
            "ts_ms": int(self.clock.now_ms()),
            "seq": self._seq,
            "extra": extra or {},
            "chrome_trace": chrome_trace_events(spans),
            "snapshot": snapshot.to_wire(),
            "frames": list(self._frames),
            "tracer": self.tracer.stats(),
        }
        payload = (
            json.dumps(doc, sort_keys=True, separators=(",", ":"), default=str)
            + "\n"
        ).encode()
        self.dumps.append(payload)
        self.last_dump = payload
        self.last_reason = reason
        self.num_dumps += 1
        if self.out_dir:
            self._write_file(reason, payload)
        self._seq += 1
        return payload

    def _write_file(self, reason: str, payload: bytes) -> None:
        import os

        safe = _REASON_SAFE.sub("_", reason) or "dump"
        path = os.path.join(
            self.out_dir, f"flight_{self.node_name}_{self._seq}_{safe}.json"
        )
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "wb") as f:
                f.write(payload)
        except OSError:
            # a full/readonly disk must never turn a post-mortem into a
            # second failure; the in-memory copy is still served
            self.counters.bump("trace.flight_dump_write_errors")

    # -- query surface -----------------------------------------------------

    def last_dump_doc(self) -> Optional[Dict[str, Any]]:
        if self.last_dump is None:
            return None
        return json.loads(self.last_dump.decode())

    def stats(self) -> Dict[str, float]:
        return {
            "trace.flight_dumps": float(self.num_dumps),
            "trace.flight_frames": float(len(self._frames)),
            "trace.flight_dumps_suppressed": float(self.num_suppressed),
        }
