"""Pipeline attribution — every millisecond of a device dispatch named.

BENCH_r02/r03 showed the end-to-end rebuild budget dominated by host
work (`host_fetch_unique_tables_ms` 1696ms, `dispatch_sync_ms` 958ms)
while the kernels took 84-150ms — but those numbers were bench-local
stopwatches.  Before the pipelined host/device rebuild (ROADMAP) can
overlap decode with compute, the live system must attribute every
dispatch to a *phase* and a *chip*, continuously, through the same
observability surfaces everything else uses.

This module is the single source of truth for the phase taxonomy:

=================  ========================================================
phase              meaning
=================  ========================================================
``host_fetch``     reading protocol state into compute form (candidate-
                   table sync, prefix/topology gathers — host memory only)
``encode``         LSDB -> padded CSR encoding (``ops/csr.py``)
``pad_pack``       bucketing/padding/shard packing of a batch
``transfer``       host->device copies (``jax.device_put``, replicas)
``device_compute`` a committed kernel dispatch; per-device attributable —
                   each shard is its own dispatch on its own chip, so the
                   sample carries a ``device`` attr exactly like rows do
``device_get``     the blocking device->host fetch draining dispatches
``decode``         device outputs -> RibUnicastEntries (host decode tail)
``delta_extract``  diffing the new RouteDb against the previous one
``warm_plan``      host-side generation-delta classification + warm-seed
                   planning (reset-set BFS, encode patch bookkeeping,
                   warm-context maintenance — decision/backend.py)
``warm_repair``    the warm-start repair kernel dispatch: re-relaxing
                   only the perturbed frontier from the previous
                   generation's device-resident tables
``stream_drain``   waiting for ONE in-flight shard to complete in the
                   streamed-completion dispatch loop; per-device
                   attributable — the window charges only the chip whose
                   shard it drained, never unrelated in-flight chips
``device_select``  the on-device delta-extraction dispatch: the fused
                   selection+changed-row kernel and the compacted
                   changed-row gather that replaces a full-table fetch
``sweep_shard_solve``  one committed capacity-sweep shard dispatch: the
                   warm-repair solve + on-device selection of a
                   scenario batch on its assigned chip
                   (openr_tpu.sweep.executor); device-attributed
``sweep_reduce``   the sweep's host tail per committed shard: spill
                   append + checkpoint commit + the online ranked
                   reducer
``protection_mint``  compacting one committed protection shard's
                   per-world route deltas into per-link FibPatches and
                   persisting them to the protection store
                   (openr_tpu.protection.builder); host tail riding the
                   sweep executor's drained deltas
``protection_apply``  the fast-reroute hot path: generation-exact
                   patch lookup + RibUnicastEntry materialization +
                   RIB splice + publish on a protected link-down event
                   (decision/decision.py)
=================  ========================================================

Surfaces: every phase sample lands in a ``pipeline.{phase}.ms``
fixed-bucket histogram and (when tracing is on) a ``pipeline.{phase}``
child span under the active trace scope; per-chip busy time accumulates
into ``pipeline.devN.busy_ms`` / ``pipeline.devN.utilization`` gauges
via :meth:`PipelineProbe.gauges` (a ``Monitor.add_counter_provider``
provider).

orlint's ``pipeline-phase-registry`` rule enforces that no other module
spells a ``pipeline.*`` name as a free string — phase names are drawn
from these constants or they do not exist.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Iterable, Optional

# -- the phase registry (the ONLY place pipeline.* names are spelled) ------

HOST_FETCH = "host_fetch"
ENCODE = "encode"
PAD_PACK = "pad_pack"
TRANSFER = "transfer"
DEVICE_COMPUTE = "device_compute"
DEVICE_GET = "device_get"
DECODE = "decode"
DELTA_EXTRACT = "delta_extract"
WARM_PLAN = "warm_plan"
WARM_REPAIR = "warm_repair"
STREAM_DRAIN = "stream_drain"
DEVICE_SELECT = "device_select"
SWEEP_SHARD_SOLVE = "sweep_shard_solve"
SWEEP_REDUCE = "sweep_reduce"
PROTECTION_MINT = "protection_mint"
PROTECTION_APPLY = "protection_apply"

PHASES = (
    HOST_FETCH,
    ENCODE,
    PAD_PACK,
    TRANSFER,
    DEVICE_COMPUTE,
    DEVICE_GET,
    DECODE,
    DELTA_EXTRACT,
    WARM_PLAN,
    WARM_REPAIR,
    STREAM_DRAIN,
    DEVICE_SELECT,
    SWEEP_SHARD_SOLVE,
    SWEEP_REDUCE,
    PROTECTION_MINT,
    PROTECTION_APPLY,
)

#: phases only the warm-start generation-delta rebuild exercises — a
#: cold full rebuild legitimately records nothing under them, so bench
#: attribution gates treat them as optional coverage
WARM_PHASES = (WARM_PLAN, WARM_REPAIR)

#: phases only the on-device delta-extraction path exercises: a build
#: whose generation delta is too wide (or whose previous outputs were
#: purged) fetches full tables and legitimately records nothing here
DELTA_PHASES = (DEVICE_SELECT,)

#: phases only the capacity-sweep orchestrator exercises
#: (openr_tpu.sweep) — route-build lifecycles record nothing here, so
#: bench attribution gates treat them as optional coverage too
SWEEP_PHASES = (SWEEP_SHARD_SOLVE, SWEEP_REDUCE)

#: phases only the fast-reroute protection tier exercises
#: (openr_tpu.protection): nodes with the tier disabled — and every
#: rebuild that isn't a protected link-down event — legitimately record
#: nothing here, so attribution gates treat them as optional coverage
PROTECTION_PHASES = (PROTECTION_MINT, PROTECTION_APPLY)

#: phases whose time is host-side work (the pipelining refactor's
#: overlap candidates) vs the device round trip — the host/device split
#: BENCH_PIPELINE reports.  ``stream_drain`` counts as device time: it
#: is the host blocked on one chip's in-flight shard (the streamed
#: replacement for the old all-shard device_get barrier).
HOST_PHASES = (
    HOST_FETCH,
    ENCODE,
    PAD_PACK,
    DECODE,
    DELTA_EXTRACT,
    WARM_PLAN,
    SWEEP_REDUCE,
    PROTECTION_MINT,
    PROTECTION_APPLY,
)
DEVICE_PHASES = (
    TRANSFER,
    DEVICE_COMPUTE,
    DEVICE_GET,
    WARM_REPAIR,
    STREAM_DRAIN,
    DEVICE_SELECT,
    SWEEP_SHARD_SOLVE,
)

_PREFIX = "pipeline."


def span_name(phase: str) -> str:
    """``pipeline.{phase}`` — the child-span name for one phase scope."""
    if phase not in PHASES:
        raise ValueError(f"unknown pipeline phase {phase!r}")
    return _PREFIX + phase


def hist_key(phase: str) -> str:
    """``pipeline.{phase}.ms`` — the fixed-bucket histogram key."""
    if phase not in PHASES:
        raise ValueError(f"unknown pipeline phase {phase!r}")
    return _PREFIX + phase + ".ms"


def device_busy_key(index: int) -> str:
    return f"{_PREFIX}dev{int(index)}.busy_ms"


def device_utilization_key(index: int) -> str:
    return f"{_PREFIX}dev{int(index)}.utilization"


import re as _re  # noqa: E402 - registry-local, keeps the prefix here

_DEVICE_KEY_RE = _re.compile(
    _re.escape(_PREFIX) + r"dev(?P<idx>\d+)\.(?P<kind>busy_ms|utilization)$"
)


def parse_device_key(key: str):
    """Inverse of the device gauge spellings: ``(index, kind)`` for a
    ``pipeline.devN.busy_ms`` / ``.utilization`` key, else None — so
    consumers (the fleet health aggregator's utilization-spread signal)
    match per-chip gauges without re-spelling the prefix."""
    m = _DEVICE_KEY_RE.match(key)
    if m is None:
        return None
    return int(m.group("idx")), m.group("kind")


class _PhaseScope:
    """Context manager for one timed phase (allocated per phase entry;
    the disabled probe short-circuits to a shared no-op instead)."""

    __slots__ = ("_probe", "_phase", "_device", "_devices", "_span", "_t0")

    def __init__(self, probe, phase, device, devices):
        self._probe = probe
        self._phase = phase
        self._device = device
        self._devices = devices

    def __enter__(self):
        probe = self._probe
        self._t0 = probe.clock.now()
        tracer = probe.tracer
        if tracer is not None and tracer.enabled:
            attrs = {}
            if self._device is not None:
                attrs["device"] = int(self._device)
            self._span = tracer.start_span(
                span_name(self._phase),
                probe._current_ctx(),
                module="pipeline",
                **attrs,
            )
        else:
            self._span = None
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        probe = self._probe
        ms = (probe.clock.now() - self._t0) * 1000.0
        if probe.counters is not None:
            probe.counters.observe(hist_key(self._phase), ms)
        if self._span is not None:
            if exc_type is not None:
                self._span.attrs["error"] = exc_type.__name__
            probe.tracer.end_span(self._span)
        if self._device is not None:
            probe.note_busy(self._device, ms)
        if self._devices:
            # a TRUE all-chip barrier charges the window to every chip
            # it covered.  The streamed-completion dispatch loops never
            # take this path any more — each stream_drain window passes
            # ``device=`` and charges ONLY the completing chip, so
            # pipeline.devN.utilization stays honest under overlap
            # (BENCH_PIPELINE_r01's mode note about fractions exceeding
            # wall share documented exactly this former overcount).
            for d in self._devices:
                probe.note_busy(d, ms)


@contextlib.contextmanager
def _noop_scope():
    yield None


class PipelineProbe:
    """Per-node phase recorder shared by the Decision backend and the
    fleet / what-if engines (they dispatch over the same DevicePool, so
    their phase samples and chip-busy time belong on one ledger).

    * timing goes through the injected Clock — SimClock runs observe
      zero-width phases deterministically instead of host-jittered ones;
    * a probe constructed without a clock is permanently disabled and
      every ``phase(...)`` entry is a shared O(1) no-op, so library
      embedders that never wire observability pay one attribute check;
    * per-chip busy time: ``device=`` charges a committed per-shard
      dispatch to its chip; ``devices=`` charges a blocking drain to
      every chip it covered.  ``gauges()`` exports
      ``pipeline.devN.busy_ms`` and ``pipeline.devN.utilization``
      (busy / probe lifetime) for the Monitor provider sweep.
    """

    def __init__(self, clock=None, counters=None, tracer=None) -> None:
        self.clock = clock
        self.counters = counters
        self.tracer = tracer
        self.enabled = clock is not None and (
            counters is not None or tracer is not None
        )
        self._busy_ms: Dict[int, float] = {}
        self._t0 = clock.now() if clock is not None else 0.0

    # -- phase scopes ------------------------------------------------------

    def phase(
        self,
        phase: str,
        device: Optional[int] = None,
        devices: Optional[Iterable[int]] = None,
    ):
        """``with probe.phase(pipeline.ENCODE): ...`` — time one phase.

        ``device`` marks a committed per-shard dispatch (chip-
        attributable sample: span carries a ``device`` attr, busy time
        charges to that chip); ``devices`` charges a blocking drain to
        every listed chip."""
        if not self.enabled:
            return _noop_scope()
        return _PhaseScope(
            self, phase, device, list(devices) if devices else None
        )

    def _current_ctx(self):
        """Parent pipeline spans under the active traced build (the
        jit_guard trace scope Decision arms around backend builds) so
        they nest beside the ``decision.spf_kernel`` spans."""
        from openr_tpu.ops import jit_guard

        scope = jit_guard._trace_scope
        return scope[1] if scope is not None else None

    # -- per-chip busy ledger ----------------------------------------------

    def note_busy(self, device: int, ms: float) -> None:
        d = int(device)
        self._busy_ms[d] = self._busy_ms.get(d, 0.0) + ms

    def busy_snapshot(self) -> Dict[int, float]:
        """Cumulative per-chip busy ms (bench deltas subtract two of
        these around a measured window)."""
        return dict(self._busy_ms)

    def gauges(self) -> Dict[str, float]:
        """Monitor.add_counter_provider provider: per-chip busy ms and
        utilization over the probe's lifetime."""
        out: Dict[str, float] = {}
        if not self.enabled:
            return out
        elapsed_ms = max((self.clock.now() - self._t0) * 1000.0, 1e-9)
        for d in sorted(self._busy_ms):
            busy = self._busy_ms[d]
            out[device_busy_key(d)] = busy
            out[device_utilization_key(d)] = min(busy / elapsed_ms, 1.0)
        return out


_DISABLED_PROBE = PipelineProbe()


def disabled_probe() -> PipelineProbe:
    """Shared always-off probe: the default for backends/engines built
    without observability wiring, so call sites never None-check."""
    return _DISABLED_PROBE
