"""Causal convergence tracing (openr_tpu.tracing).

A `Tracer` (one per node, injected `Clock` so SimClock tests get
deterministic timestamps) mints `TraceContext`s at event origins and
modules record spans against contexts they receive through queue items
and KvStore flooding metadata.  `export` renders completed spans as a
Chrome-trace/Perfetto-compatible file.  See docs/Observability.md for
the span taxonomy and naming conventions.
"""

from openr_tpu.tracing.export import chrome_trace_events, write_chrome_trace
from openr_tpu.tracing.tracer import NOOP_SPAN, Span, Tracer, disabled_tracer

__all__ = [
    "NOOP_SPAN",
    "Span",
    "Tracer",
    "chrome_trace_events",
    "disabled_tracer",
    "write_chrome_trace",
]
