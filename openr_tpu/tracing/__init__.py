"""Causal convergence tracing (openr_tpu.tracing).

A `Tracer` (one per node, injected `Clock` so SimClock tests get
deterministic timestamps) mints `TraceContext`s at event origins and
modules record spans against contexts they receive through queue items
and KvStore flooding metadata.  `export` renders completed spans as a
Chrome-trace/Perfetto-compatible file.  `pipeline` holds the dispatch
phase registry + `PipelineProbe` (per-phase histograms, per-chip busy
gauges); `flight_recorder` the bounded post-mortem ring that auto-dumps
on invariant breach / chip quarantine / watchdog crash.  See
docs/Observability.md for the span taxonomy and naming conventions.
"""

from openr_tpu.tracing.export import chrome_trace_events, write_chrome_trace
from openr_tpu.tracing.flight_recorder import FlightRecorder
from openr_tpu.tracing.pipeline import PipelineProbe, disabled_probe
from openr_tpu.tracing.tracer import NOOP_SPAN, Span, Tracer, disabled_tracer

__all__ = [
    "NOOP_SPAN",
    "FlightRecorder",
    "PipelineProbe",
    "Span",
    "Tracer",
    "chrome_trace_events",
    "disabled_probe",
    "disabled_tracer",
    "write_chrome_trace",
]
