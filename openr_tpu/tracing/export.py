"""Chrome-trace / Perfetto export of completed spans.

Renders spans in the Chrome Trace Event Format (the JSON Array Format:
``[`` + one complete event object per line + ``]``, which both
``chrome://tracing`` and ui.perfetto.dev open directly).  Each node maps
to a pid (with a ``process_name`` metadata record) and each module to a
tid within it, so an emulated multi-node run shows one swimlane block
per node with per-module tracks.

Event mapping: a closed span becomes one complete event (``"ph": "X"``,
``ts``/``dur`` in microseconds); trace/span/parent ids and span attrs
ride in ``args`` so the viewer's selection pane shows the causal links.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List


def _wire(span) -> Dict[str, Any]:
    return span if isinstance(span, dict) else span.to_wire()


def chrome_trace_events(spans: Iterable) -> List[Dict[str, Any]]:
    """Spans (Span objects or their to_wire dicts) -> Chrome trace events.
    Open spans (end_ms None) are skipped — the viewer rejects X events
    without a duration."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    meta: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    for raw in spans:
        s = _wire(raw)
        if not s or s.get("end_ms") is None:
            continue
        node = s.get("node", "")
        module = s.get("module") or s.get("name", "").split(".", 1)[0]
        # chip-attributed spans (`decision.spf_kernel` shard dispatches,
        # `resilience.probe` probes, `pipeline.device_compute`) get one
        # lane PER CHIP so quarantine/probe/dispatch timelines line up
        # per device in Perfetto instead of interleaving on one module
        # track
        device = (s.get("attrs") or {}).get("device")
        if device is not None:
            module = f"{module}.dev{device}"
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            meta.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": node},
                }
            )
        tkey = (node, module)
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = sum(1 for k in tids if k[0] == node) + 1
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": module},
                }
            )
        events.append(
            {
                "name": s["name"],
                "cat": "openr",
                "ph": "X",
                "ts": round(s["start_ms"] * 1000.0, 3),
                "dur": round(
                    max(s["end_ms"] - s["start_ms"], 0.0) * 1000.0, 3
                ),
                "pid": pid,
                "tid": tid,
                "args": {
                    "trace_id": s["trace_id"],
                    "span_id": s["span_id"],
                    "parent_id": s["parent_id"],
                    **s.get("attrs", {}),
                },
            }
        )
    return meta + events


def write_chrome_trace(path: str, spans: Iterable) -> int:
    """Write one event per line inside a JSON array (line-oriented for
    grep/tail, still a single valid JSON document for the viewers).
    Returns the number of events written."""
    events = chrome_trace_events(spans)
    with open(path, "w") as f:
        f.write("[\n")
        f.write(",\n".join(json.dumps(e, sort_keys=True) for e in events))
        f.write("\n]\n")
    return len(events)
