"""Thrift Compact protocol: a spec-driven reader/writer.

The reference daemon serializes every flooded LSDB payload with
``apache::thrift::CompactSerializer`` (AdjacencyDatabase under
``adj:<node>``, PrefixDatabase under ``prefix:...`` — LinkMonitor.h:369,
KvStoreUtil-inl.h:20), so speaking this encoding is what makes the
framework's data plane byte-compatible with a live openr network: our
tools can decode its floods and emit values its nodes accept.  The RPC
*transport* (fbthrift Rocket) remains out of scope — see README "Wire
format"; this module is the struct layer a bridge would sit on.

Implemented from the public Thrift Compact protocol spec
(thrift/doc/specs/thrift-compact-protocol.md):

  * varint       = ULEB128;  i16/i32/i64 are zigzag'd first
  * field header = (delta << 4) | ctype for id deltas 1..15, else the
    ctype byte followed by the zigzag-varint field id; BOOL fields fold
    the value into the ctype (1 = true, 2 = false); 0x00 ends a struct
  * binary       = varint length + bytes (strings are UTF-8)
  * list/set     = (size << 4) | elem-ctype, or 0xF? + varint size when
    size >= 15; bool elements are bytes 1/2
  * map          = 0x00 when empty, else varint size then one
    (key-ctype << 4) | value-ctype byte and alternating k/v
  * double       = IEEE-754 bits, LITTLE-endian (the apache C++/Java
    implementations' byte order, which fbthrift matches)

Structs are described by specs: ``(field_id, name, type, arg)`` tuples
where ``arg`` carries the element spec for containers or the nested
spec for structs.  Decoding skips unknown fields, so newer peers stay
readable (forward compatibility).
"""

from __future__ import annotations

import struct as _struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

# wire-level compact type codes (NOT the TType codes)
CT_STOP = 0x00
CT_BOOL_TRUE = 0x01
CT_BOOL_FALSE = 0x02
CT_BYTE = 0x03
CT_I16 = 0x04
CT_I32 = 0x05
CT_I64 = 0x06
CT_DOUBLE = 0x07
CT_BINARY = 0x08
CT_LIST = 0x09
CT_SET = 0x0A
CT_MAP = 0x0B
CT_STRUCT = 0x0C

#: spec type names -> compact wire type for field/element headers
_WIRE_OF = {
    "bool": CT_BOOL_TRUE,  # container/element form; fields special-case
    "byte": CT_BYTE,
    "i16": CT_I16,
    "i32": CT_I32,
    "i64": CT_I64,
    "double": CT_DOUBLE,
    "binary": CT_BINARY,
    "string": CT_BINARY,
    "list": CT_LIST,
    "set": CT_SET,
    "map": CT_MAP,
    "struct": CT_STRUCT,
}

#: a struct spec: ordered (field_id, name, type, arg) rows.  arg is the
#: element spec for list/set ((etype, earg)), a ((ktype, karg),
#: (vtype, varg)) pair for maps, or the nested StructSpec for structs.
StructSpec = Sequence[Tuple[int, str, str, Any]]


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class CompactWriter:
    def __init__(self) -> None:
        self._buf = bytearray()

    def getvalue(self) -> bytes:
        return bytes(self._buf)

    # -- primitives --------------------------------------------------------

    def write_varint(self, n: int) -> None:
        if n < 0:
            n &= (1 << 64) - 1  # two's-complement into ULEB128
        b = self._buf
        while True:
            if n < 0x80:
                b.append(n)
                return
            b.append((n & 0x7F) | 0x80)
            n >>= 7

    def write_zigzag(self, n: int) -> None:
        self.write_varint(_zigzag(n))

    def write_byte(self, n: int) -> None:
        self._buf.append(n & 0xFF)

    def write_double(self, d: float) -> None:
        self._buf += _struct.pack("<d", d)

    def write_binary(self, data: bytes) -> None:
        self.write_varint(len(data))
        self._buf += data

    # -- spec-driven struct ------------------------------------------------

    def write_struct(self, spec: StructSpec, obj: Dict[str, Any]) -> None:
        last_fid = 0
        for fid, name, ftype, arg in spec:
            val = obj.get(name)
            if val is None:
                continue  # unset / optional
            if ftype == "bool":
                ct = CT_BOOL_TRUE if val else CT_BOOL_FALSE
            else:
                ct = _WIRE_OF[ftype]
            delta = fid - last_fid
            if 0 < delta <= 15:
                self.write_byte((delta << 4) | ct)
            else:
                self.write_byte(ct)
                self.write_zigzag(fid)
            last_fid = fid
            if ftype != "bool":
                self._write_value(ftype, arg, val)
        self.write_byte(CT_STOP)

    def _write_value(self, ftype: str, arg: Any, val: Any) -> None:
        if ftype == "bool":
            self.write_byte(CT_BOOL_TRUE if val else CT_BOOL_FALSE)
        elif ftype == "byte":
            self.write_byte(val)
        elif ftype in ("i16", "i32", "i64"):
            self.write_zigzag(int(val))
        elif ftype == "double":
            self.write_double(val)
        elif ftype == "string":
            self.write_binary(val.encode("utf-8"))
        elif ftype == "binary":
            self.write_binary(bytes(val))
        elif ftype in ("list", "set"):
            etype, earg = arg
            # sets encode SORTED: fbthrift C++ serializes thrift sets
            # from std::set (ordered), and Python set iteration order is
            # hash-seed dependent — unsorted emission would make our
            # bytes nondeterministic across processes and never stably
            # match the reference's for 2+ elements
            items = sorted(val) if ftype == "set" else list(val)
            ect = _WIRE_OF[etype]
            if len(items) < 15:
                self.write_byte((len(items) << 4) | ect)
            else:
                self.write_byte(0xF0 | ect)
                self.write_varint(len(items))
            for item in items:
                self._write_value(etype, earg, item)
        elif ftype == "map":
            (ktype, karg), (vtype, varg) = arg
            # maps encode SORTED BY KEY for the same determinism reason
            # as sets: dict insertion order varies across processes, and
            # self-emitted Publication/linkStatusMap bytes must be
            # stable.  (Reference bytes are nondeterministic here anyway
            # — fbthrift C++ KeyVals is std::unordered_map — so sorting
            # costs no compatibility.)
            items = sorted(val.items(), key=lambda kv: kv[0])
            if not items:
                self.write_byte(0)
                return
            self.write_varint(len(items))
            self.write_byte((_WIRE_OF[ktype] << 4) | _WIRE_OF[vtype])
            for k, v in items:
                self._write_value(ktype, karg, k)
                self._write_value(vtype, varg, v)
        elif ftype == "struct":
            self.write_struct(arg, val)
        else:  # pragma: no cover
            raise ValueError(f"unknown thrift spec type {ftype!r}")


#: per-spec field-id lookup cache: specs are module-level constant
#: tuples, and rebuilding the {fid: row} dict for every decoded struct
#: instance (every adjacency of every flooded publication on the
#: Decision hot path) is pure waste.
#:
#: ASSUMPTION: specs are module-level constants (openr_wire.py and the
#: test corpus).  The cache holds a strong reference to every spec it
#: has seen, so a caller constructing specs dynamically at runtime pins
#: each one forever — don't do that, or decode with
#: ``CompactReader(data)._read_struct_fields({...})`` built by hand.
#: (Tuples don't support weakrefs, so a WeakValueDictionary can't
#: express the bounded variant.)
_BY_ID_CACHE: Dict[int, tuple] = {}


def _by_id(spec: StructSpec) -> Dict[int, tuple]:
    # keyed by id(spec) but verified by identity AND keeping the spec
    # referenced: a gc'd dynamic spec whose address got reused must not
    # hit a stale entry (silent wrong-field decodes)
    cached = _BY_ID_CACHE.get(id(spec))
    if cached is not None and cached[0] is spec:
        return cached[1]
    by_id = {fid: (name, ftype, arg) for fid, name, ftype, arg in spec}
    _BY_ID_CACHE[id(spec)] = (spec, by_id)
    return by_id


#: sentinel returned by _read_value when a container's declared element
#: wire type disagrees with the spec: the container's bytes have been
#: consumed (stream stays in sync) but the value is untrustworthy — the
#: field degrades to unset, matching the field-level wire-type check
_MISMATCH = object()


def _elem_type_ok(ect: int, etype: str) -> bool:
    """Does a container header's element ctype match the spec type?

    Bool container elements encode as one byte 0x01/0x02, and writers
    may declare either code in the header."""
    if etype == "bool":
        return ect in (CT_BOOL_TRUE, CT_BOOL_FALSE)
    return _WIRE_OF.get(etype) == ect


#: untrusted input guard: crafted bytes like 0x1C repeated (every byte a
#: nested-struct field header) recurse once per level — cap well above
#: any real Open/R struct (max nesting ~4) but far below Python's
#: recursion limit so garbage fails as ValueError, not RecursionError
_MAX_DEPTH = 32


class CompactReader:
    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0
        self._depth = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("truncated compact payload")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def read_byte(self) -> int:
        return self._take(1)[0]

    def read_varint(self) -> int:
        shift = 0
        out = 0
        while True:
            b = self.read_byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")

    def read_zigzag(self) -> int:
        return _unzigzag(self.read_varint())

    def read_double(self) -> float:
        return _struct.unpack("<d", self._take(8))[0]

    def read_binary(self) -> bytes:
        return self._take(self.read_varint())

    # -- spec-driven struct ------------------------------------------------

    def read_struct(self, spec: StructSpec) -> Dict[str, Any]:
        by_id = _by_id(spec)
        self._enter()
        try:
            return self._read_struct_fields(by_id)
        finally:
            self._depth -= 1

    def _enter(self) -> None:
        self._depth += 1
        if self._depth > _MAX_DEPTH:
            raise ValueError(
                f"compact payload nests deeper than {_MAX_DEPTH} structs"
            )

    def _read_struct_fields(self, by_id: Dict[int, tuple]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        last_fid = 0
        while True:
            head = self.read_byte()
            if head == CT_STOP:
                return out
            delta = (head >> 4) & 0x0F
            ct = head & 0x0F
            fid = last_fid + delta if delta else self.read_zigzag()
            last_fid = fid
            row = by_id.get(fid)
            if ct in (CT_BOOL_TRUE, CT_BOOL_FALSE):
                val: Any = ct == CT_BOOL_TRUE
            elif row is not None and _WIRE_OF.get(row[1]) == ct:
                # decode by spec ONLY when the wire type agrees — a peer
                # that changed a field's type (or a spec mistake) must
                # degrade to a skipped field, not desync the byte stream
                val = self._read_value(row[1], row[2])
                if val is _MISMATCH:
                    # container whose ELEMENT type disagreed with the
                    # spec: bytes consumed in sync, field left unset
                    continue
            else:
                self._skip(ct)
                continue
            if row is not None and (
                row[1] == "bool" or _WIRE_OF.get(row[1]) == ct
            ):
                out[row[0]] = val
            # otherwise: unknown field, or known field whose wire type
            # disagrees with the spec — consumed/skipped, not stored

    def _read_value(self, ftype: str, arg: Any) -> Any:
        if ftype == "bool":
            return self.read_byte() == CT_BOOL_TRUE
        if ftype == "byte":
            b = self.read_byte()
            return b - 256 if b >= 128 else b
        if ftype in ("i16", "i32", "i64"):
            return self.read_zigzag()
        if ftype == "double":
            return self.read_double()
        if ftype == "string":
            return self.read_binary().decode("utf-8")
        if ftype == "binary":
            return self.read_binary()
        if ftype in ("list", "set"):
            etype, earg = arg
            head = self.read_byte()
            size = (head >> 4) & 0x0F
            if size == 0x0F:
                size = self.read_varint()
            ect = head & 0x0F
            if size and not _elem_type_ok(ect, etype):
                # peer changed the element type: skip the container by
                # its DECLARED wire type so the stream stays in sync,
                # surface the mismatch so the field degrades to unset
                self._skip_list_elems(ect, size)
                return _MISMATCH
            items = [self._read_value(etype, earg) for _ in range(size)]
            if any(item is _MISMATCH for item in items):
                return _MISMATCH  # nested container element mismatched
            return set(items) if ftype == "set" else items
        if ftype == "map":
            (ktype, karg), (vtype, varg) = arg
            size = self.read_varint()
            if not size:
                return {}
            kv = self.read_byte()  # (key-ctype << 4) | value-ctype
            if not (
                _elem_type_ok((kv >> 4) & 0x0F, ktype)
                and _elem_type_ok(kv & 0x0F, vtype)
            ):
                self._skip_map_elems(kv, size)
                return _MISMATCH
            out: Dict[Any, Any] = {}
            mismatched = False
            for _ in range(size):
                k = self._read_value(ktype, karg)
                v = self._read_value(vtype, varg)
                if k is _MISMATCH or v is _MISMATCH:
                    mismatched = True
                else:
                    out[k] = v
            return _MISMATCH if mismatched else out
        if ftype == "struct":
            return self.read_struct(arg)
        raise ValueError(f"unknown thrift spec type {ftype!r}")

    def _skip_list_elems(self, ect: int, size: int) -> None:
        """Skip ``size`` list/set elements of wire type ``ect``; crafted
        nested containers recurse like structs, so depth-guard."""
        self._enter()
        try:
            for _ in range(size):
                self._skip(ect)
        finally:
            self._depth -= 1

    def _skip_map_elems(self, kv: int, size: int) -> None:
        """Skip ``size`` map entries given the packed kv-types byte."""
        self._enter()
        try:
            for _ in range(size):
                self._skip((kv >> 4) & 0x0F)
                self._skip(kv & 0x0F)
        finally:
            self._depth -= 1

    def _skip(self, ct: int) -> None:
        """Skip one unknown value of wire type ``ct`` (forward compat).

        Only container/element contexts reach the bool branch (a bool
        STRUCT FIELD folds its value into the field header's type code
        and both skip call sites handle that before dispatching here),
        and container bool elements occupy one byte."""
        if ct in (CT_BOOL_TRUE, CT_BOOL_FALSE):
            self.read_byte()
            return
        if ct == CT_BYTE:
            self.read_byte()
        elif ct in (CT_I16, CT_I32, CT_I64):
            self.read_varint()
        elif ct == CT_DOUBLE:
            self._take(8)
        elif ct == CT_BINARY:
            self.read_binary()
        elif ct in (CT_LIST, CT_SET):
            head = self.read_byte()
            size = (head >> 4) & 0x0F
            if size == 0x0F:
                size = self.read_varint()
            self._skip_list_elems(head & 0x0F, size)
        elif ct == CT_MAP:
            size = self.read_varint()
            if size:
                self._skip_map_elems(self.read_byte(), size)
        elif ct == CT_STRUCT:
            self._enter()
            try:
                while True:
                    head = self.read_byte()
                    if head == CT_STOP:
                        return
                    if not (head >> 4) & 0x0F:
                        self.read_zigzag()  # long-form field id
                    inner = head & 0x0F
                    if inner in (CT_BOOL_TRUE, CT_BOOL_FALSE):
                        continue  # field bools fold the value in the type
                    self._skip(inner)
            finally:
                self._depth -= 1
        else:
            raise ValueError(f"cannot skip compact wire type {ct}")


def encode_struct(spec: StructSpec, obj: Dict[str, Any]) -> bytes:
    w = CompactWriter()
    w.write_struct(spec, obj)
    return w.getvalue()


def decode_struct(spec: StructSpec, data: bytes) -> Dict[str, Any]:
    return CompactReader(data).read_struct(spec)
