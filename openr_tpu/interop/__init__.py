"""fbthrift wire interop: Thrift Compact protocol codec + Open/R struct
specs, so this framework can decode (and emit) the byte-level payloads a
reference openr network floods — see openr_tpu/interop/compact.py and
openr_wire.py.  The RPC *transport* lives here too: RSocket 1.0 framing
(rsocket.py), the fbthrift Rocket layer (rocket.py), and the ctrl
method-name adapter + server (ctrl_rocket.py)."""

from openr_tpu.interop.openr_wire import (  # noqa: F401
    decode_adjacency_database,
    decode_prefix_database,
    decode_publication,
    decode_route_database,
    decode_value,
    encode_adjacency_database,
    encode_prefix_database,
    encode_publication,
    encode_route_database,
    encode_value,
)
