"""RSocket 1.0 frame codec — the framing layer under fbthrift Rocket.

The reference's entire RPC plane is fbthrift's "Rocket" transport: the
ctrl server (`/root/reference/openr/Main.cpp:399-416`), every KvStore
peer session (`/root/reference/openr/kvstore/KvStore.h:460-466`) and the
py3 CLI client (`/root/reference/openr/py/openr/clients/openr_client.py`)
all speak thrift RPCs over RSocket frames on TCP.  This module
implements the RSocket 1.0 wire format from the public protocol spec
(rsocket.io/about/protocol) — frame types, flag bits and section
layouts follow that document; the fbthrift-specific payload contents
live one layer up in `openr_tpu.interop.rocket`.

Layout notes (all integers big-endian):

  stream frame  := u24 length | frame
  frame         := u32 stream_id | u16 (type << 10 | flags) | body
  payload       := [u24 metadata-length | metadata] data      (M flag)

Fragmentation (FOLLOWS flag) is not emitted and not reassembled: every
thrift struct this framework exchanges is far below the default 16 MiB
fragment threshold; a FOLLOWS frame raises so truncation can never be
silent.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

# -- frame types (RSocket 1.0 §5.4) ---------------------------------------
FT_SETUP = 0x01
FT_LEASE = 0x02
FT_KEEPALIVE = 0x03
FT_REQUEST_RESPONSE = 0x04
FT_REQUEST_FNF = 0x05
FT_REQUEST_STREAM = 0x06
FT_REQUEST_CHANNEL = 0x07
FT_REQUEST_N = 0x08
FT_CANCEL = 0x09
FT_PAYLOAD = 0x0A
FT_ERROR = 0x0B
FT_METADATA_PUSH = 0x0C
FT_RESUME = 0x0D
FT_RESUME_OK = 0x0E
FT_EXT = 0x3F

#: flag bits within the 10-bit flags field.  IGNORE/METADATA are common;
#: the rest are per-type and share bit positions.
FLAG_IGNORE = 0x200
FLAG_METADATA = 0x100
FLAG_RESUME = 0x080  # SETUP
FLAG_LEASE = 0x040  # SETUP
FLAG_RESPOND = 0x080  # KEEPALIVE
FLAG_FOLLOWS = 0x080  # REQUEST_*, PAYLOAD
FLAG_COMPLETE = 0x040  # PAYLOAD, REQUEST_CHANNEL
FLAG_NEXT = 0x020  # PAYLOAD

# -- error codes (RSocket 1.0 §5.9) ---------------------------------------
ERR_INVALID_SETUP = 0x00000001
ERR_UNSUPPORTED_SETUP = 0x00000002
ERR_REJECTED_SETUP = 0x00000003
ERR_CONNECTION_ERROR = 0x00000101
ERR_APPLICATION_ERROR = 0x00000201
ERR_REJECTED = 0x00000202
ERR_CANCELED = 0x00000203
ERR_INVALID = 0x00000204

MAX_FRAME = 16 * 1024 * 1024


@dataclass
class Frame:
    """One decoded RSocket frame.  Fields beyond (stream_id, ftype,
    flags, metadata, data) are type-specific and default-zero."""

    stream_id: int
    ftype: int
    flags: int
    metadata: Optional[bytes] = None
    data: bytes = b""
    # SETUP
    major: int = 0
    minor: int = 0
    keepalive_ms: int = 0
    max_lifetime_ms: int = 0
    metadata_mime: str = ""
    data_mime: str = ""
    # KEEPALIVE
    last_position: int = 0
    # REQUEST_STREAM / REQUEST_CHANNEL / REQUEST_N
    initial_n: int = 0
    # ERROR
    error_code: int = 0

    @property
    def error_message(self) -> str:
        return self.data.decode("utf-8", "replace")


def _header(stream_id: int, ftype: int, flags: int) -> bytes:
    return struct.pack(">IH", stream_id, (ftype << 10) | (flags & 0x3FF))


def _payload_sections(
    flags: int, metadata: Optional[bytes], data: bytes
) -> tuple:
    """-> (flags', bytes): add METADATA flag + u24 length when present."""
    if metadata is None:
        return flags, data
    if len(metadata) >= 1 << 24:
        raise ValueError("rsocket metadata exceeds u24 length")
    return (
        flags | FLAG_METADATA,
        len(metadata).to_bytes(3, "big") + metadata + data,
    )


def encode_setup(
    *,
    keepalive_ms: int,
    max_lifetime_ms: int,
    metadata_mime: str,
    data_mime: str,
    metadata: Optional[bytes] = None,
    data: bytes = b"",
    major: int = 1,
    minor: int = 0,
) -> bytes:
    """SETUP (§5.4.1), always stream 0.  Resume/lease unsupported."""
    flags, payload = _payload_sections(0, metadata, data)
    mm = metadata_mime.encode("ascii")
    dm = data_mime.encode("ascii")
    return (
        _header(0, FT_SETUP, flags)
        + struct.pack(">HHII", major, minor, keepalive_ms, max_lifetime_ms)
        + bytes([len(mm)])
        + mm
        + bytes([len(dm)])
        + dm
        + payload
    )


def encode_keepalive(last_position: int = 0, *, respond: bool, data: bytes = b"") -> bytes:
    flags = FLAG_RESPOND if respond else 0
    return (
        _header(0, FT_KEEPALIVE, flags)
        + struct.pack(">Q", last_position)
        + data
    )


def encode_request_response(
    stream_id: int, metadata: Optional[bytes], data: bytes
) -> bytes:
    flags, payload = _payload_sections(0, metadata, data)
    return _header(stream_id, FT_REQUEST_RESPONSE, flags) + payload


def encode_request_fnf(
    stream_id: int, metadata: Optional[bytes], data: bytes
) -> bytes:
    flags, payload = _payload_sections(0, metadata, data)
    return _header(stream_id, FT_REQUEST_FNF, flags) + payload


def encode_request_stream(
    stream_id: int, initial_n: int, metadata: Optional[bytes], data: bytes
) -> bytes:
    flags, payload = _payload_sections(0, metadata, data)
    return (
        _header(stream_id, FT_REQUEST_STREAM, flags)
        + struct.pack(">I", initial_n)
        + payload
    )


def encode_request_n(stream_id: int, n: int) -> bytes:
    return _header(stream_id, FT_REQUEST_N, 0) + struct.pack(">I", n)


def encode_cancel(stream_id: int) -> bytes:
    return _header(stream_id, FT_CANCEL, 0)


def encode_payload(
    stream_id: int,
    metadata: Optional[bytes],
    data: bytes,
    *,
    complete: bool = False,
    next_: bool = True,
) -> bytes:
    flags = (FLAG_COMPLETE if complete else 0) | (FLAG_NEXT if next_ else 0)
    flags, payload = _payload_sections(flags, metadata, data)
    return _header(stream_id, FT_PAYLOAD, flags) + payload


def encode_error(stream_id: int, code: int, message: str = "") -> bytes:
    return (
        _header(stream_id, FT_ERROR, 0)
        + struct.pack(">I", code)
        + message.encode("utf-8")
    )


def _split_payload(flags: int, body: bytes) -> tuple:
    """-> (metadata | None, data) per the M flag."""
    if not flags & FLAG_METADATA:
        return None, body
    if len(body) < 3:
        raise ValueError("truncated rsocket metadata length")
    mlen = int.from_bytes(body[:3], "big")
    if 3 + mlen > len(body):
        raise ValueError("truncated rsocket metadata")
    return body[3 : 3 + mlen], body[3 + mlen :]


def decode_frame(raw: bytes) -> Frame:
    """Decode one frame (without the u24 stream-length prefix).

    All malformed input — truncated bodies included — raises ValueError
    so connection handlers need exactly one except clause."""
    try:
        return _decode_frame(raw)
    except (struct.error, IndexError) as e:
        raise ValueError(f"truncated rsocket frame body: {e}") from e


def _decode_frame(raw: bytes) -> Frame:
    if len(raw) < 6:
        raise ValueError("rsocket frame shorter than header")
    stream_id, tf = struct.unpack(">IH", raw[:6])
    if stream_id & 0x80000000:
        raise ValueError("rsocket stream id has reserved high bit set")
    ftype = tf >> 10
    flags = tf & 0x3FF
    body = raw[6:]
    f = Frame(stream_id=stream_id, ftype=ftype, flags=flags)
    if flags & FLAG_FOLLOWS and ftype in (
        FT_REQUEST_RESPONSE,
        FT_REQUEST_FNF,
        FT_REQUEST_STREAM,
        FT_REQUEST_CHANNEL,
        FT_PAYLOAD,
    ):
        raise ValueError(
            "rsocket fragmentation (FOLLOWS) not supported; frame exceeds "
            "peer's fragment threshold"
        )
    if ftype == FT_SETUP:
        if len(body) < 14:
            raise ValueError("truncated SETUP frame")
        f.major, f.minor, f.keepalive_ms, f.max_lifetime_ms = struct.unpack(
            ">HHII", body[:12]
        )
        pos = 12
        if flags & FLAG_RESUME:
            tlen = int.from_bytes(body[pos : pos + 2], "big")
            pos += 2 + tlen  # token ignored (resume unsupported)
        mlen = body[pos]
        f.metadata_mime = body[pos + 1 : pos + 1 + mlen].decode("ascii")
        pos += 1 + mlen
        dlen = body[pos]
        f.data_mime = body[pos + 1 : pos + 1 + dlen].decode("ascii")
        pos += 1 + dlen
        f.metadata, f.data = _split_payload(flags, body[pos:])
    elif ftype == FT_KEEPALIVE:
        (f.last_position,) = struct.unpack(">Q", body[:8])
        f.data = body[8:]
    elif ftype in (FT_REQUEST_STREAM, FT_REQUEST_CHANNEL):
        (f.initial_n,) = struct.unpack(">I", body[:4])
        f.metadata, f.data = _split_payload(flags, body[4:])
    elif ftype == FT_REQUEST_N:
        (f.initial_n,) = struct.unpack(">I", body[:4])
    elif ftype == FT_ERROR:
        (f.error_code,) = struct.unpack(">I", body[:4])
        f.data = body[4:]
    elif ftype in (
        FT_REQUEST_RESPONSE,
        FT_REQUEST_FNF,
        FT_PAYLOAD,
        FT_METADATA_PUSH,
        FT_CANCEL,
    ):
        f.metadata, f.data = _split_payload(flags, body)
    else:
        # LEASE/RESUME/EXT…: not used by fbthrift request-response; keep
        # the raw body so callers can IGNORE-skip per the spec
        f.data = body
    return f


# -- stream framing (u24 length prefix, RSocket over TCP §4) ---------------


def frame_stream(frame: bytes) -> bytes:
    """Prefix one frame with its u24 length for a byte-stream transport."""
    if len(frame) > MAX_FRAME:
        raise ValueError(f"rsocket frame too large: {len(frame)}")
    return len(frame).to_bytes(3, "big") + frame


async def read_stream_frame(reader) -> Optional[Frame]:
    """Read one length-prefixed frame from an asyncio StreamReader; None
    on clean EOF / connection drop."""
    import asyncio

    try:
        head = await reader.readexactly(3)
        length = int.from_bytes(head, "big")
        if length > MAX_FRAME:
            raise ValueError(f"rsocket frame too large: {length}")
        raw = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return decode_frame(raw)
