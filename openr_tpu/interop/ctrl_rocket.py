"""fbthrift-Rocket ctrl adapter: reference method names -> this daemon.

The reference's operator and peer planes are one thrift service
(`/root/reference/openr/if/OpenrCtrl.thrift:251-741`, KvStore service
`/root/reference/openr/if/KvStore.thrift:474-560`) served over Rocket.
This module is the thin adapter the round-4 review scoped: a table
mapping each thrift METHOD NAME to (argument struct spec, result struct
spec, declared exception) plus a binding into the existing modules, so a
reference-encoded RPC round-trips end-to-end through `RocketServer`:

    rsocket REQUEST_RESPONSE
      -> RequestRpcMetadata.name  -> METHODS[name]
      -> compact-decode args      -> module call
      -> compact-encode result    -> PAYLOAD (NEXT|COMPLETE)

Declared exceptions (``OpenrError``/``KvStoreError``, both
``{1: string message}``) are returned fbthrift-style: the result struct
carries the exception field and ResponseRpcMetadata.payloadMetadata is
``exceptionMetadata{declaredException}``.

The adapted subset is the peer-sync plane plus the core operator reads
(the round-4 scope): getKvStoreKeyValsFilteredArea, setKvStoreKeyVals,
getDecisionAdjacenciesFiltered, getRouteDbComputed, and the close
variants that share their arg shapes.  The table is data — each further
method is one row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from openr_tpu import types as T
from openr_tpu.interop import rocket
from openr_tpu.interop.compact import decode_struct, encode_struct
from openr_tpu.interop.openr_wire import (
    ADJACENCY_DATABASE,
    PUBLICATION,
    ROUTE_DATABASE,
    VALUE,
    adjacency_database_to_wire_obj,
    publication_from_wire_obj,
    publication_to_wire_obj,
    route_database_to_wire_obj,
    value_to_wire_obj,
)

# -- request/exception struct specs (reference IDL field ids) ---------------

#: KvStore.thrift:241 KeyDumpParams
KEY_DUMP_PARAMS = (
    (2, "keyValHashes", "map", (("string", None), ("struct", VALUE))),
    (3, "originatorIds", "set", ("string", None)),
    (4, "oper", "i32", None),
    (5, "keys", "list", ("string", None)),
    (6, "ignoreTtl", "bool", None),
    (7, "doNotPublishValue", "bool", None),
    (8, "senderId", "string", None),
)

#: KvStore.thrift:203 KeySetParams
KEY_SET_PARAMS = (
    (2, "keyVals", "map", (("string", None), ("struct", VALUE))),
    (5, "nodeIds", "list", ("string", None)),
    (7, "timestamp_ms", "i64", None),
    (8, "senderId", "string", None),
)

#: OpenrCtrl.thrift:108 AdjacenciesFilter
ADJACENCIES_FILTER = ((1, "selectAreas", "set", ("string", None)),)

#: OpenrError (OpenrCtrl.thrift:24) and KvStoreError (KvStore.thrift:87)
#: share the shape {1: string message}
THRIFT_EXCEPTION = ((1, "message", "string", None),)


class DeclaredError(Exception):
    """Module failure to surface as the method's declared thrift
    exception rather than an rsocket APPLICATION_ERROR."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


@dataclass
class MethodSpec:
    args: tuple  # compact spec of the args struct
    #: (ftype, arg) of the success value, or None for void
    success: Optional[Tuple[str, Any]]
    error_name: str  # thrift exception type name for declared errors
    bind: Callable[[Any, Dict[str, Any]], Awaitable[Any]]


def _default_area(node) -> str:
    try:
        return node.config.areas[0].area_id
    except AttributeError:
        return "0"


def _hashes_from_key_vals(kv: Dict[str, dict]) -> Dict[str, tuple]:
    """thrift KeyVals digests -> KvStore (version, originator, hash)."""
    return {
        k: (
            int(v.get("version", 0)),
            v.get("originatorId", ""),
            v.get("hash"),
        )
        for k, v in kv.items()
    }


async def _get_kv_store_key_vals_filtered_area(
    node, args: Dict[str, Any]
) -> Dict[str, Any]:
    f = args.get("filter") or {}
    area = args.get("area") or _default_area(node)
    sender = f.get("senderId", "")
    hashes = f.get("keyValHashes")
    if hashes is not None:
        # anti-entropy 3-way sync (KvStore-inl.h:2153): respond with
        # newer values + the keys the initiator must push back.  A
        # PRESENT-but-empty map is still a sync request (cold initiator):
        # it must flow through handle_full_sync_request so values get the
        # flood-copy TTL decrement, not the plain operator dump
        try:
            pub = await node.kv_store.handle_full_sync_request(
                area, _hashes_from_key_vals(hashes), sender
            )
        except Exception as e:  # noqa: BLE001 — unknown area etc.
            raise DeclaredError(str(e)) from e
        return publication_to_wire_obj(pub)
    # plain filtered dump
    keys = list(f.get("keys") or [])
    originators = sorted(f.get("originatorIds") or [])
    store = node.kv_store
    if area not in store.areas:
        raise DeclaredError(f"unknown area {area!r}")
    vals: Dict[str, T.Value] = {}
    for pref in keys or [""]:
        vals.update(store.dump_all(area, pref))
    if originators:
        want = set(originators)
        vals = {k: v for k, v in vals.items() if v.originator_id in want}
    key_vals = {}
    for k, v in vals.items():
        row = value_to_wire_obj(v)
        if f.get("doNotPublishValue"):
            row.pop("value", None)
        key_vals[k] = row
    return {"keyVals": key_vals, "area": area}


async def _set_kv_store_key_vals(node, args: Dict[str, Any]) -> None:
    sp = args.get("setParams") or {}
    area = args.get("area") or _default_area(node)
    pub = publication_from_wire_obj(
        {
            "keyVals": sp.get("keyVals") or {},
            "nodeIds": sp.get("nodeIds"),
            "timestamp_ms": sp.get("timestamp_ms"),
            "area": area,
        }
    )
    node_ids = sp.get("nodeIds") or []
    sender = sp.get("senderId") or (node_ids[-1] if node_ids else "")
    try:
        await node.kv_store.handle_set_key_vals(area, pub, sender)
    except Exception as e:  # noqa: BLE001
        raise DeclaredError(str(e)) from e


async def _get_decision_adjacencies_filtered(
    node, args: Dict[str, Any]
) -> list:
    f = args.get("filter") or {}
    areas = sorted(f.get("selectAreas") or [])
    dbs = []
    for a in areas or [None]:
        dbs.extend(node.decision.get_adj_dbs(a))
    return [adjacency_database_to_wire_obj(db) for db in dbs]


async def _get_route_db_computed(node, args: Dict[str, Any]) -> Dict[str, Any]:
    name = args.get("nodeName") or node.name
    db = node.decision.compute_route_db_for_node(name)
    if db is None:
        return {"thisNodeName": name, "unicastRoutes": [], "mplsRoutes": []}
    return route_database_to_wire_obj(db.to_route_database(name))


async def _get_kv_store_key_vals_area(node, args: Dict[str, Any]) -> dict:
    """getKvStoreKeyValsArea: exact-key get (KvStore.thrift:487)."""
    area = args.get("area") or _default_area(node)
    store = node.kv_store
    if area not in store.areas:
        raise DeclaredError(f"unknown area {area!r}")
    vals = store.get_key_vals(area, list(args.get("filterKeys") or []))
    return {
        "keyVals": {k: value_to_wire_obj(v) for k, v in vals.items()},
        "area": area,
    }


#: Types.thrift:750 OpenrVersions
OPENR_VERSIONS = (
    (1, "version", "i32", None),
    (2, "lowestSupportedVersion", "i32", None),
)

#: KvStore.thrift:302 PeerSpec (the response subset: addr/port/state)
PEER_SPEC = (
    (1, "peerAddr", "string", None),
    (4, "ctrlPort", "i32", None),
    (5, "state", "i32", None),
)


async def _get_openr_version(node, args: Dict[str, Any]) -> Dict[str, Any]:
    from openr_tpu import constants as _C

    return {
        "version": _C.OPENR_VERSION,
        "lowestSupportedVersion": _C.OPENR_SUPPORTED_VERSION,
    }


async def _get_route_db(node, args: Dict[str, Any]) -> Dict[str, Any]:
    db = node.decision.get_route_db().to_route_database(node.name)
    return route_database_to_wire_obj(db)


async def _get_kv_store_peers(node, args: Dict[str, Any]) -> Dict[str, Any]:
    area = args.get("area") or _default_area(node)
    db = node.kv_store.areas.get(area)
    if db is None:
        raise DeclaredError(f"unknown area {area!r}")
    return {
        name: {
            "peerAddr": peer.spec.peer_addr,
            "ctrlPort": peer.spec.ctrl_port,
            "state": int(peer.state),
        }
        for name, peer in db.peers.items()
    }


METHODS: Dict[str, MethodSpec] = {
    "getOpenrVersion": MethodSpec(
        args=(),
        success=("struct", OPENR_VERSIONS),
        error_name="OpenrError",
        bind=_get_openr_version,
    ),
    "getRouteDb": MethodSpec(
        args=(),
        success=("struct", ROUTE_DATABASE),
        error_name="OpenrError",
        bind=_get_route_db,
    ),
    "getKvStorePeers": MethodSpec(
        args=(),
        success=("map", (("string", None), ("struct", PEER_SPEC))),
        error_name="KvStoreError",
        bind=_get_kv_store_peers,
    ),
    "getKvStorePeersArea": MethodSpec(
        args=((1, "area", "string", None),),
        success=("map", (("string", None), ("struct", PEER_SPEC))),
        error_name="KvStoreError",
        bind=_get_kv_store_peers,
    ),
    "getKvStoreKeyValsFilteredArea": MethodSpec(
        args=(
            (1, "filter", "struct", KEY_DUMP_PARAMS),
            (2, "area", "string", None),
        ),
        success=("struct", PUBLICATION),
        error_name="KvStoreError",
        bind=_get_kv_store_key_vals_filtered_area,
    ),
    "getKvStoreKeyValsArea": MethodSpec(
        args=(
            (1, "filterKeys", "list", ("string", None)),
            (2, "area", "string", None),
        ),
        success=("struct", PUBLICATION),
        error_name="KvStoreError",
        bind=_get_kv_store_key_vals_area,
    ),
    "setKvStoreKeyVals": MethodSpec(
        args=(
            (1, "setParams", "struct", KEY_SET_PARAMS),
            (2, "area", "string", None),
        ),
        success=None,
        error_name="KvStoreError",
        bind=_set_kv_store_key_vals,
    ),
    "getDecisionAdjacenciesFiltered": MethodSpec(
        args=((1, "filter", "struct", ADJACENCIES_FILTER),),
        success=("list", ("struct", ADJACENCY_DATABASE)),
        error_name="OpenrError",
        bind=_get_decision_adjacencies_filtered,
    ),
    "getRouteDbComputed": MethodSpec(
        args=((1, "nodeName", "string", None),),
        success=("struct", ROUTE_DATABASE),
        error_name="OpenrError",
        bind=_get_route_db_computed,
    ),
}


def _build_result_spec(m: MethodSpec) -> tuple:
    """Compact spec of the method's result struct: field 0 success (when
    non-void) + field 1 declared exception."""
    rows = []
    if m.success is not None:
        ftype, arg = m.success
        rows.append((0, "success", ftype, arg))
    rows.append((1, "error", "struct", THRIFT_EXCEPTION))
    return tuple(rows)


#: built ONCE per method: compact.py's _BY_ID_CACHE pins every spec it
#: sees forever (module-constant assumption), so constructing a fresh
#: result spec per RPC would leak one cache entry per call on the
#: KvStore peer hot path
RESULT_SPECS: Dict[str, tuple] = {
    name: _build_result_spec(m) for name, m in METHODS.items()
}


class RocketCtrlService:
    """Dispatch target for `rocket.RocketServer` bridging into one node's
    modules (the OpenrCtrlHandler equivalent of the thrift surface)."""

    def __init__(self, node):
        self.node = node

    async def dispatch(
        self, name: str, data: bytes, peer: object
    ) -> Tuple[bytes, bytes]:
        m = METHODS.get(name)
        if m is None:
            raise rocket.RocketError(f"unknown thrift method {name!r}")
        args = decode_struct(m.args, data)
        counters = getattr(self.node, "counters", None)
        if counters is not None:
            counters.bump(f"ctrl.rocket.{name}")
        rspec = RESULT_SPECS[name]
        try:
            value = await m.bind(self.node, args)
        except DeclaredError as e:
            rmeta = rocket.encode_response_metadata(
                exception=(m.error_name, e.message, True)
            )
            result = encode_struct(rspec, {"error": {"message": e.message}})
            return rmeta, result
        obj: Dict[str, Any] = {}
        if m.success is not None:
            obj["success"] = value
        return rocket.encode_response_metadata(), encode_struct(rspec, obj)


class RocketCtrlServer(rocket.RocketServer):
    """fbthrift-Rocket listener for one node (the reference's
    ThriftServer role, Main.cpp:399-416).  In `lsdb_rpc_transport:
    "rocket"` deployments this is what peers dial on the ctrl port."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0, tls=None):
        self.node = node
        self.service = RocketCtrlService(node)
        ctx = None
        if tls is not None:
            from openr_tpu.common.tls import server_ssl_context

            ctx = server_ssl_context(tls)
        self.tls_active = ctx is not None
        super().__init__(self.service.dispatch, host=host, port=port, ssl=ctx)


# -- client-side helpers (what a py3 openr client does) ---------------------


async def rocket_call(
    client: rocket.RocketClient,
    name: str,
    args_obj: Dict[str, Any],
    *,
    timeout_s: float = 30.0,
) -> Any:
    """Encode args, call, decode result; raise DeclaredError/RocketError."""
    m = METHODS.get(name)
    if m is None:
        raise rocket.RocketError(f"unknown thrift method {name!r}")
    resp = await client.request_response(
        name, encode_struct(m.args, args_obj), timeout_s=timeout_s
    )
    try:
        result = decode_struct(RESULT_SPECS[name], resp.data)
    except ValueError as e:
        # the PEER's response bytes are garbage — a session-health event
        # (RocketCodecError → teardown), not a local programming bug
        raise rocket.RocketCodecError(
            f"malformed response payload for {name!r}: {e}"
        ) from e
    exc = resp.exception
    if "error" in result or exc is not None:
        msg = (result.get("error") or {}).get("message") or (
            (exc or {}).get("what_utf8", "")
        )
        raise DeclaredError(msg)
    return result.get("success")
