"""Open/R wire-struct specs + adapters to this framework's dataclasses.

Field ids mirror the reference IDL (schema compatibility):
AdjacencyDatabase/Adjacency/PrefixEntry/PrefixDatabase/PerfEvents from
``openr/if/Types.thrift``, Value/Publication from
``openr/if/KvStore.thrift``, BinaryAddress/IpPrefix/NextHopThrift/
UnicastRoute/MplsRoute/RouteDatabase/MplsAction from
``openr/if/Network.thrift``.  Encoded bytes are what
``apache::thrift::CompactSerializer`` produces for the same structs —
the payloads a reference node floods in its KvStore values and serves
from its ctrl API.

Adapters convert between the thrift shapes and ``openr_tpu.types``
dataclasses: addresses go packed-``BinaryAddress`` <-> string IPs,
prefixes go ``IpPrefix`` <-> ``"net/len"`` strings, enums are numeric on
the wire on both sides.
"""

from __future__ import annotations

import ipaddress
from typing import Any, Dict, Optional

from openr_tpu import types as T
from openr_tpu.interop.compact import decode_struct, encode_struct

# -- struct specs (field_id, name, type, arg) -------------------------------

BINARY_ADDRESS = (
    (1, "addr", "binary", None),
    (3, "ifName", "string", None),
)

IP_PREFIX = (
    (1, "prefixAddress", "struct", BINARY_ADDRESS),
    (2, "prefixLength", "i16", None),
)

PERF_EVENT = (
    (1, "nodeName", "string", None),
    (2, "eventDescr", "string", None),
    (3, "unixTs", "i64", None),
)

PERF_EVENTS = ((1, "events", "list", ("struct", PERF_EVENT)),)

ADJACENCY = (
    (1, "otherNodeName", "string", None),
    (2, "ifName", "string", None),
    (3, "nextHopV6", "struct", BINARY_ADDRESS),
    (4, "metric", "i32", None),
    (5, "nextHopV4", "struct", BINARY_ADDRESS),
    (6, "adjLabel", "i32", None),
    (7, "isOverloaded", "bool", None),
    (8, "rtt", "i32", None),
    (9, "timestamp", "i64", None),
    (10, "weight", "i64", None),
    (11, "otherIfName", "string", None),
    (12, "adjOnlyUsedByOtherNode", "bool", None),
)

LINK_STATUS = (
    (1, "status", "i32", None),
    (2, "unixTs", "i64", None),
)

LINK_STATUS_RECORDS = (
    (1, "linkStatusMap", "map", (("string", None), ("struct", LINK_STATUS))),
)

ADJACENCY_DATABASE = (
    (1, "thisNodeName", "string", None),
    (2, "isOverloaded", "bool", None),
    (3, "adjacencies", "list", ("struct", ADJACENCY)),
    (4, "nodeLabel", "i32", None),
    (5, "perfEvents", "struct", PERF_EVENTS),
    (6, "area", "string", None),
    (7, "nodeMetricIncrementVal", "i32", None),
    (8, "linkStatusRecords", "struct", LINK_STATUS_RECORDS),
)

PREFIX_METRICS = (
    (1, "version", "i32", None),
    (2, "path_preference", "i32", None),
    (3, "source_preference", "i32", None),
    (4, "distance", "i32", None),
    (5, "drain_metric", "i32", None),
)

PREFIX_ENTRY = (
    (1, "prefix", "struct", IP_PREFIX),
    (2, "type", "i32", None),
    (4, "forwardingType", "i32", None),
    (7, "forwardingAlgorithm", "i32", None),
    (8, "minNexthop", "i64", None),
    (10, "metrics", "struct", PREFIX_METRICS),
    (11, "tags", "set", ("string", None)),
    (12, "area_stack", "list", ("string", None)),
    (13, "weight", "i64", None),
)

PREFIX_DATABASE = (
    (1, "thisNodeName", "string", None),
    (3, "prefixEntries", "list", ("struct", PREFIX_ENTRY)),
    (4, "perfEvents", "struct", PERF_EVENTS),
    (5, "deletePrefix", "bool", None),
)

VALUE = (
    (1, "version", "i64", None),
    (2, "value", "binary", None),
    (3, "originatorId", "string", None),
    (4, "ttl", "i64", None),
    (5, "ttlVersion", "i64", None),
    (6, "hash", "i64", None),
)

PUBLICATION = (
    (2, "keyVals", "map", (("string", None), ("struct", VALUE))),
    (3, "expiredKeys", "list", ("string", None)),
    (4, "nodeIds", "list", ("string", None)),
    (5, "tobeUpdatedKeys", "list", ("string", None)),
    (7, "area", "string", None),
    (8, "timestamp_ms", "i64", None),
)

MPLS_ACTION = (
    (1, "action", "i32", None),
    (2, "swapLabel", "i32", None),
    (3, "pushLabels", "list", ("i32", None)),
)

NEXT_HOP = (
    (1, "address", "struct", BINARY_ADDRESS),
    (2, "weight", "i32", None),
    (3, "mplsAction", "struct", MPLS_ACTION),
    (51, "metric", "i32", None),
    (53, "area", "string", None),
    (54, "neighborNodeName", "string", None),
)

UNICAST_ROUTE = (
    (1, "dest", "struct", IP_PREFIX),
    (4, "nextHops", "list", ("struct", NEXT_HOP)),
)

MPLS_ROUTE = (
    (1, "topLabel", "i32", None),
    (4, "nextHops", "list", ("struct", NEXT_HOP)),
)

ROUTE_DATABASE = (
    (1, "thisNodeName", "string", None),
    (3, "perfEvents", "struct", PERF_EVENTS),
    (4, "unicastRoutes", "list", ("struct", UNICAST_ROUTE)),
    (5, "mplsRoutes", "list", ("struct", MPLS_ROUTE)),
)


# -- address/prefix conversions ---------------------------------------------


def _addr_to_wire(ip: str, if_name: str = "") -> Optional[Dict[str, Any]]:
    if not ip and not if_name:
        return None
    d: Dict[str, Any] = {
        "addr": ipaddress.ip_address(ip).packed if ip else b""
    }
    if if_name:
        d["ifName"] = if_name
    return d


def _addr_from_wire(d: Optional[Dict[str, Any]]) -> tuple:
    """-> (ip string, ifName)"""
    if not d or not d.get("addr"):
        return "", (d or {}).get("ifName", "")
    return (
        ipaddress.ip_address(d["addr"]).compressed,
        d.get("ifName", ""),
    )


def _prefix_to_wire(prefix: str) -> Dict[str, Any]:
    net = ipaddress.ip_network(prefix, strict=False)
    return {
        "prefixAddress": {"addr": net.network_address.packed},
        "prefixLength": net.prefixlen,
    }


def _prefix_from_wire(d: Dict[str, Any]) -> str:
    ip, _ = _addr_from_wire(d["prefixAddress"])
    return f"{ip}/{d['prefixLength']}"


# -- AdjacencyDatabase ------------------------------------------------------


def adjacency_database_to_wire_obj(db: T.AdjacencyDatabase) -> Dict[str, Any]:
    """Thrift-field-name dict form (the shape fed to ADJACENCY_DATABASE),
    reusable where the struct nests inside an RPC envelope."""
    adjacencies = []
    for a in db.adjacencies:
        row: Dict[str, Any] = {
            "otherNodeName": a.other_node_name,
            "ifName": a.if_name,
            "metric": a.metric,
            "adjLabel": a.adj_label,
            "isOverloaded": a.is_overloaded,
            "rtt": a.rtt,
            "timestamp": a.timestamp,
            "weight": a.weight,
            "otherIfName": a.other_if_name,
            "adjOnlyUsedByOtherNode": a.adj_only_used_by_other_node,
        }
        v6 = _addr_to_wire(a.next_hop_v6)
        v4 = _addr_to_wire(a.next_hop_v4)
        # the reference always carries both nexthop structs
        row["nextHopV6"] = v6 or {"addr": b""}
        row["nextHopV4"] = v4 or {"addr": b""}
        adjacencies.append(row)
    obj: Dict[str, Any] = {
        "thisNodeName": db.this_node_name,
        "isOverloaded": db.is_overloaded,
        "adjacencies": adjacencies,
        "nodeLabel": db.node_label,
        "area": db.area,
        "nodeMetricIncrementVal": db.node_metric_increment_val,
    }
    if db.perf_events is not None:
        obj["perfEvents"] = _perf_to_wire(db.perf_events)
    if db.link_status_records is not None:
        obj["linkStatusRecords"] = {
            "linkStatusMap": {
                ifn: {"status": int(st), "unixTs": ts}
                for ifn, (st, ts) in (
                    db.link_status_records.link_status_map.items()
                )
            }
        }
    return obj


def encode_adjacency_database(db: T.AdjacencyDatabase) -> bytes:
    return encode_struct(ADJACENCY_DATABASE, adjacency_database_to_wire_obj(db))


def adjacency_database_from_wire_obj(d: Dict[str, Any]) -> T.AdjacencyDatabase:
    adjacencies = []
    for row in d.get("adjacencies", []):
        v6, _ = _addr_from_wire(row.get("nextHopV6"))
        v4, _ = _addr_from_wire(row.get("nextHopV4"))
        adjacencies.append(
            T.Adjacency(
                other_node_name=row.get("otherNodeName", ""),
                if_name=row.get("ifName", ""),
                metric=row.get("metric", 1),
                adj_label=row.get("adjLabel", 0),
                is_overloaded=row.get("isOverloaded", False),
                rtt=row.get("rtt", 0),
                timestamp=row.get("timestamp", 0),
                weight=row.get("weight", 1),
                other_if_name=row.get("otherIfName", ""),
                adj_only_used_by_other_node=row.get(
                    "adjOnlyUsedByOtherNode", False
                ),
                next_hop_v6=v6,
                next_hop_v4=v4,
            )
        )
    lsr = None
    if "linkStatusRecords" in d:
        lsr = T.LinkStatusRecords(
            link_status_map={
                ifn: (int(st.get("status", 0)), int(st.get("unixTs", 0)))
                for ifn, st in d["linkStatusRecords"]
                .get("linkStatusMap", {})
                .items()
            }
        )
    return T.AdjacencyDatabase(
        this_node_name=d.get("thisNodeName", ""),
        is_overloaded=d.get("isOverloaded", False),
        adjacencies=adjacencies,
        node_label=d.get("nodeLabel", 0),
        perf_events=_perf_from_wire(d.get("perfEvents")),
        area=d.get("area", "0"),
        node_metric_increment_val=d.get("nodeMetricIncrementVal", 0),
        link_status_records=lsr,
    )


def decode_adjacency_database(data: bytes) -> T.AdjacencyDatabase:
    return adjacency_database_from_wire_obj(
        decode_struct(ADJACENCY_DATABASE, data)
    )


# -- PrefixDatabase ---------------------------------------------------------


def _perf_to_wire(pe: T.PerfEvents) -> Dict[str, Any]:
    return {
        "events": [
            {
                "nodeName": e.node_name,
                "eventDescr": e.event_descr,
                "unixTs": e.unix_ts_ms,
            }
            for e in pe.events
        ]
    }


def _perf_from_wire(d: Optional[Dict[str, Any]]) -> Optional[T.PerfEvents]:
    if d is None:
        return None
    return T.PerfEvents(
        events=[
            T.PerfEvent(
                node_name=e.get("nodeName", ""),
                event_descr=e.get("eventDescr", ""),
                unix_ts_ms=e.get("unixTs", 0),
            )
            for e in d.get("events", [])
        ]
    )


def encode_prefix_database(db: T.PrefixDatabase) -> bytes:
    entries = []
    for p in db.prefix_entries:
        row: Dict[str, Any] = {
            "prefix": _prefix_to_wire(p.prefix),
            "type": int(p.type),
            "forwardingType": int(p.forwarding_type),
            "forwardingAlgorithm": int(p.forwarding_algorithm),
            "metrics": {
                "version": p.metrics.version,
                "path_preference": p.metrics.path_preference,
                "source_preference": p.metrics.source_preference,
                "distance": p.metrics.distance,
                "drain_metric": p.metrics.drain_metric,
            },
            "tags": set(p.tags),
            "area_stack": list(p.area_stack),
        }
        if p.min_nexthop is not None:
            row["minNexthop"] = p.min_nexthop
        if p.weight is not None:
            row["weight"] = p.weight
        entries.append(row)
    obj: Dict[str, Any] = {
        "thisNodeName": db.this_node_name,
        "prefixEntries": entries,
        "deletePrefix": db.delete_prefix,
    }
    if db.perf_events is not None:
        obj["perfEvents"] = _perf_to_wire(db.perf_events)
    return encode_struct(PREFIX_DATABASE, obj)


def decode_prefix_database(data: bytes) -> T.PrefixDatabase:
    d = decode_struct(PREFIX_DATABASE, data)
    entries = []
    for row in d.get("prefixEntries", []):
        m = row.get("metrics", {})
        entries.append(
            T.PrefixEntry(
                prefix=_prefix_from_wire(row["prefix"]),
                type=T.PrefixType(row.get("type", int(T.PrefixType.LOOPBACK))),
                forwarding_type=T.PrefixForwardingType(
                    row.get("forwardingType", 0)
                ),
                forwarding_algorithm=T.PrefixForwardingAlgorithm(
                    row.get("forwardingAlgorithm", 0)
                ),
                min_nexthop=row.get("minNexthop"),
                metrics=T.PrefixMetrics(
                    version=m.get("version", 1),
                    drain_metric=m.get("drain_metric", 0),
                    path_preference=m.get("path_preference", 0),
                    source_preference=m.get("source_preference", 0),
                    distance=m.get("distance", 0),
                ),
                tags=set(row.get("tags", ())),
                area_stack=list(row.get("area_stack", ())),
                weight=row.get("weight"),
            )
        )
    return T.PrefixDatabase(
        this_node_name=d.get("thisNodeName", ""),
        prefix_entries=entries,
        perf_events=_perf_from_wire(d.get("perfEvents")),
        delete_prefix=d.get("deletePrefix", False),
    )


# -- KvStore Value / Publication --------------------------------------------


def encode_value(v: T.Value) -> bytes:
    obj: Dict[str, Any] = {
        "version": v.version,
        "originatorId": v.originator_id,
        "ttl": v.ttl,
        "ttlVersion": v.ttl_version,
    }
    if v.value is not None:
        obj["value"] = v.value
    if v.hash is not None:
        obj["hash"] = v.hash
    return encode_struct(VALUE, obj)


def _value_from_wire(d: Dict[str, Any]) -> T.Value:
    return T.Value(
        version=d.get("version", 0),
        originator_id=d.get("originatorId", ""),
        value=d.get("value"),
        ttl=d.get("ttl", -1),
        ttl_version=d.get("ttlVersion", 0),
        hash=d.get("hash"),
    )


def decode_value(data: bytes) -> T.Value:
    return _value_from_wire(decode_struct(VALUE, data))


def value_to_wire_obj(v: T.Value) -> Dict[str, Any]:
    row: Dict[str, Any] = {
        "version": v.version,
        "originatorId": v.originator_id,
        "ttl": v.ttl,
        "ttlVersion": v.ttl_version,
    }
    if v.value is not None:
        row["value"] = v.value
    if v.hash is not None:
        row["hash"] = v.hash
    return row


def publication_to_wire_obj(pub: T.Publication) -> Dict[str, Any]:
    key_vals = {}
    for k, v in pub.key_vals.items():
        key_vals[k] = value_to_wire_obj(v)
    obj: Dict[str, Any] = {
        "keyVals": key_vals,
        "expiredKeys": list(pub.expired_keys),
        "area": pub.area,
    }
    if pub.node_ids is not None:
        obj["nodeIds"] = list(pub.node_ids)
    if pub.tobe_updated_keys is not None:
        obj["tobeUpdatedKeys"] = list(pub.tobe_updated_keys)
    if pub.timestamp_ms is not None:
        obj["timestamp_ms"] = pub.timestamp_ms
    return obj


def encode_publication(pub: T.Publication) -> bytes:
    return encode_struct(PUBLICATION, publication_to_wire_obj(pub))


def publication_from_wire_obj(d: Dict[str, Any]) -> T.Publication:
    return T.Publication(
        key_vals={
            k: _value_from_wire(v) for k, v in d.get("keyVals", {}).items()
        },
        expired_keys=list(d.get("expiredKeys", ())),
        node_ids=d.get("nodeIds"),
        tobe_updated_keys=d.get("tobeUpdatedKeys"),
        area=d.get("area", "0"),
        timestamp_ms=d.get("timestamp_ms"),
    )


def decode_publication(data: bytes) -> T.Publication:
    return publication_from_wire_obj(decode_struct(PUBLICATION, data))


# -- RouteDatabase ----------------------------------------------------------


def _nexthop_to_wire(nh: T.NextHop) -> Dict[str, Any]:
    row: Dict[str, Any] = {
        "address": _addr_to_wire(nh.address, nh.if_name) or {"addr": b""},
        "weight": nh.weight,
        "metric": nh.metric,
    }
    if nh.area:
        row["area"] = nh.area
    if nh.neighbor_node_name:
        row["neighborNodeName"] = nh.neighbor_node_name
    if nh.mpls_action is not None:
        ma: Dict[str, Any] = {"action": int(nh.mpls_action.action)}
        if nh.mpls_action.swap_label is not None:
            ma["swapLabel"] = nh.mpls_action.swap_label
        if nh.mpls_action.push_labels is not None:
            ma["pushLabels"] = list(nh.mpls_action.push_labels)
        row["mplsAction"] = ma
    return row


def _nexthop_from_wire(row: Dict[str, Any]) -> T.NextHop:
    ip, ifn = _addr_from_wire(row.get("address"))
    ma = None
    if "mplsAction" in row:
        w = row["mplsAction"]
        ma = T.MplsAction(
            action=T.MplsActionCode(w.get("action", 0)),
            swap_label=w.get("swapLabel"),
            push_labels=(
                tuple(w["pushLabels"]) if "pushLabels" in w else None
            ),
        )
    return T.NextHop(
        address=ip,
        if_name=ifn,
        metric=row.get("metric", 0),
        weight=row.get("weight", 0),
        area=row.get("area", ""),
        neighbor_node_name=row.get("neighborNodeName", ""),
        mpls_action=ma,
    )


def route_database_to_wire_obj(db: T.RouteDatabase) -> Dict[str, Any]:
    obj: Dict[str, Any] = {
        "thisNodeName": db.this_node_name,
        "unicastRoutes": [
            {
                "dest": _prefix_to_wire(r.dest),
                "nextHops": [_nexthop_to_wire(nh) for nh in r.next_hops],
            }
            for r in db.unicast_routes
        ],
        "mplsRoutes": [
            {
                "topLabel": r.top_label,
                "nextHops": [_nexthop_to_wire(nh) for nh in r.next_hops],
            }
            for r in db.mpls_routes
        ],
    }
    if db.perf_events is not None:
        obj["perfEvents"] = _perf_to_wire(db.perf_events)
    return obj


def encode_route_database(db: T.RouteDatabase) -> bytes:
    return encode_struct(ROUTE_DATABASE, route_database_to_wire_obj(db))


def route_database_from_wire_obj(d: Dict[str, Any]) -> T.RouteDatabase:
    return T.RouteDatabase(
        this_node_name=d.get("thisNodeName", ""),
        unicast_routes=[
            T.UnicastRoute(
                dest=_prefix_from_wire(r["dest"]),
                next_hops=[
                    _nexthop_from_wire(nh) for nh in r.get("nextHops", [])
                ],
            )
            for r in d.get("unicastRoutes", [])
        ],
        mpls_routes=[
            T.MplsRoute(
                top_label=r.get("topLabel", 0),
                next_hops=[
                    _nexthop_from_wire(nh) for nh in r.get("nextHops", [])
                ],
            )
            for r in d.get("mplsRoutes", [])
        ],
        perf_events=_perf_from_wire(d.get("perfEvents")),
    )


def decode_route_database(data: bytes) -> T.RouteDatabase:
    return route_database_from_wire_obj(decode_struct(ROUTE_DATABASE, data))
