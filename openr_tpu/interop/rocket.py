"""fbthrift Rocket transport: thrift RPC over RSocket frames.

This is the transport the reference speaks everywhere
(`/root/reference/openr/Main.cpp:399-416` ThriftServer,
`/root/reference/openr/kvstore/KvStore.h:460-466` peer clients): each
thrift call becomes one RSocket REQUEST_RESPONSE frame whose *metadata*
is a Compact-serialized ``RequestRpcMetadata`` (method name, protocol,
rpc kind) and whose *data* is the Compact-serialized argument struct;
the response is a PAYLOAD frame (NEXT|COMPLETE) carrying a
``ResponseRpcMetadata`` plus the Compact-serialized result struct
(field 0 = success, declared-exception fields as in the IDL).

Sources: the public fbthrift rocket protocol spec
(thrift/doc/specs/fbthrift-rocket-protocol.md) and the public
``thrift/lib/thrift/RpcMetadata.thrift`` field numbering.  Connection
establishment: a SETUP frame on stream 0 whose metadata is the 32-bit
big-endian ``kRocketProtocolKey`` (= 1) followed by a Compact
``RequestSetupMetadata``; client streams are odd ids starting at 1.
Golden byte vectors for all of this are pinned in
``tests/test_rocket.py`` so any framing regression is caught at the
byte level, the same way ``tests/test_thrift_interop.py`` pins structs.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from openr_tpu.common.runtime import Clock, WallClock
from openr_tpu.interop import rsocket as rs
from openr_tpu.interop.compact import decode_struct, encode_struct

LOG = logging.getLogger(__name__)

#: fbthrift's magic distinguishing its rocket dialect in SETUP metadata
ROCKET_PROTOCOL_KEY = 1

#: ProtocolId (RpcMetadata.thrift): serialization of args/result structs
PROTOCOL_BINARY = 0
PROTOCOL_COMPACT = 2

#: RpcKind (RpcMetadata.thrift)
RPC_SINGLE_REQUEST_SINGLE_RESPONSE = 0
RPC_SINGLE_REQUEST_NO_RESPONSE = 1
RPC_SINGLE_REQUEST_STREAMING_RESPONSE = 4

#: mime types carried in SETUP; fbthrift sets these but dispatches on the
#: protocol key in the metadata, so they are informational
MIME = "text/plain"

KEEPALIVE_MS = 30_000
MAX_LIFETIME_MS = 3_600_000

# -- RpcMetadata.thrift struct specs (public field numbering) --------------

REQUEST_SETUP_METADATA = (
    (1, "opaque", "map", (("string", None), ("binary", None))),
    (2, "minVersion", "i32", None),
    (3, "maxVersion", "i32", None),
    (4, "dscpToReflect", "i32", None),
    (5, "markToReflect", "i32", None),
)

REQUEST_RPC_METADATA = (
    (1, "protocol", "i32", None),
    (2, "name", "string", None),
    (3, "kind", "i32", None),
    (5, "clientTimeoutMs", "i32", None),
    (6, "queueTimeoutMs", "i32", None),
    (7, "priority", "i32", None),
    (8, "otherMetadata", "map", (("string", None), ("string", None))),
)

#: PayloadResponseMetadata is an empty struct
PAYLOAD_RESPONSE_METADATA: tuple = ()

#: PayloadExceptionMetadata union — only the variants we emit/understand
PAYLOAD_EXCEPTION_METADATA = (
    (1, "declaredException", "struct", ()),
    (5, "appUnknownException", "struct", ()),
)

PAYLOAD_EXCEPTION_METADATA_BASE = (
    (1, "name_utf8", "string", None),
    (2, "what_utf8", "string", None),
    (3, "metadata", "struct", PAYLOAD_EXCEPTION_METADATA),
)

#: PayloadMetadata union
PAYLOAD_METADATA = (
    (1, "responseMetadata", "struct", PAYLOAD_RESPONSE_METADATA),
    (2, "exceptionMetadata", "struct", PAYLOAD_EXCEPTION_METADATA_BASE),
)

RESPONSE_RPC_METADATA = (
    (1, "load", "i64", None),
    (2, "otherMetadata", "map", (("string", None), ("string", None))),
    (3, "payloadMetadata", "struct", PAYLOAD_METADATA),
)


def encode_setup_metadata(setup: Optional[Dict[str, Any]] = None) -> bytes:
    """SETUP metadata: u32 kRocketProtocolKey | Compact RequestSetupMetadata."""
    body = encode_struct(
        REQUEST_SETUP_METADATA,
        setup if setup is not None else {"minVersion": 0, "maxVersion": 0},
    )
    return ROCKET_PROTOCOL_KEY.to_bytes(4, "big") + body


def decode_setup_metadata(md: bytes) -> Dict[str, Any]:
    if len(md) < 4 or int.from_bytes(md[:4], "big") != ROCKET_PROTOCOL_KEY:
        raise ValueError("SETUP metadata does not carry kRocketProtocolKey")
    return decode_struct(REQUEST_SETUP_METADATA, md[4:])


def encode_request_metadata(
    name: str,
    kind: int = RPC_SINGLE_REQUEST_SINGLE_RESPONSE,
    *,
    protocol: int = PROTOCOL_COMPACT,
    client_timeout_ms: Optional[int] = None,
    other: Optional[Dict[str, str]] = None,
) -> bytes:
    obj: Dict[str, Any] = {"protocol": protocol, "name": name, "kind": kind}
    if client_timeout_ms is not None:
        obj["clientTimeoutMs"] = client_timeout_ms
    if other:
        obj["otherMetadata"] = other
    return encode_struct(REQUEST_RPC_METADATA, obj)


def encode_response_metadata(
    *,
    exception: Optional[Tuple[str, str, bool]] = None,
    other: Optional[Dict[str, str]] = None,
) -> bytes:
    """``exception`` = (thrift type name, message, declared?)."""
    obj: Dict[str, Any] = {}
    if other:
        obj["otherMetadata"] = other
    if exception is None:
        obj["payloadMetadata"] = {"responseMetadata": {}}
    else:
        name, what, declared = exception
        obj["payloadMetadata"] = {
            "exceptionMetadata": {
                "name_utf8": name,
                "what_utf8": what,
                "metadata": (
                    {"declaredException": {}}
                    if declared
                    else {"appUnknownException": {}}
                ),
            }
        }
    return encode_struct(RESPONSE_RPC_METADATA, obj)


class RocketError(RuntimeError):
    """Transport- or application-level rocket failure."""

    def __init__(self, message: str, *, code: int = 0, name: str = ""):
        super().__init__(message)
        self.code = code
        self.name = name  # thrift exception type for declared exceptions


class RocketCodecError(RocketError):
    """The PEER sent bytes this side cannot decode (malformed or
    incompatible compact payload / response metadata).  Kept distinct
    from bare ValueError on purpose: a ValueError out of OUR encode path
    is a programming bug and must propagate loudly, while a peer's
    garbage response is a session-health event (teardown + redial) —
    the KvStore transport catch sites key on exactly this split."""


@dataclass
class RocketResponse:
    metadata: Dict[str, Any]
    data: bytes

    @property
    def exception(self) -> Optional[Dict[str, Any]]:
        pm = self.metadata.get("payloadMetadata") or {}
        return pm.get("exceptionMetadata")


class RocketClient:
    """Minimal fbthrift-rocket client: SETUP + multiplexed
    request-response (+ fire-and-forget), with keepalive echo."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        ssl=None,
        setup: Optional[dict] = None,
        keepalive_ms: int = KEEPALIVE_MS,
        clock: Optional[Clock] = None,
    ):
        self.host = host
        self.port = port
        self._ssl = ssl
        self._setup = setup
        self._keepalive_ms = keepalive_ms
        self._clock = clock if clock is not None else WallClock()
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1, 2)  # client streams are odd
        self._pending: Dict[int, asyncio.Future] = {}
        self._pump_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._closed = False
        #: terminal failure: once set, every further call fails fast
        #: instead of parking a future nothing can resolve (a peer that
        #: closed while we were idle must not cost the next RPC a 30 s
        #: timeout before the transport redials)
        self._dead: Optional[Exception] = None

    async def connect(self) -> "RocketClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self._ssl
        )
        self._writer.write(
            rs.frame_stream(
                rs.encode_setup(
                    keepalive_ms=self._keepalive_ms,
                    max_lifetime_ms=MAX_LIFETIME_MS,
                    metadata_mime=MIME,
                    data_mime=MIME,
                    metadata=encode_setup_metadata(self._setup),
                )
            )
        )
        await self._writer.drain()
        self._pump_task = asyncio.create_task(self._pump())
        # RSocket 1.0 obliges the client to emit KEEPALIVE at the
        # interval it declared in SETUP; a spec-compliant responder may
        # drop a silent connection after max_lifetime
        self._keepalive_task = asyncio.create_task(self._keepalive_loop())
        return self

    async def close(self) -> None:
        self._closed = True
        for task in (self._pump_task, self._keepalive_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
        self._fail_pending(RocketError("rocket connection closed"))

    async def __aenter__(self) -> "RocketClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    def _fail_pending(self, err: Exception) -> None:
        self._dead = err
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    async def _keepalive_loop(self) -> None:
        try:
            while True:
                await self._clock.sleep(self._keepalive_ms / 1000.0)
                self._writer.write(
                    rs.frame_stream(rs.encode_keepalive(0, respond=True))
                )
                await self._writer.drain()
        except asyncio.CancelledError:
            raise
        except (ConnectionError, OSError) as e:
            self._fail_pending(RocketError(f"rocket keepalive failed: {e}"))

    async def _pump(self) -> None:
        try:
            while True:
                frame = await rs.read_stream_frame(self._reader)
                if frame is None:
                    self._fail_pending(RocketError("rocket peer closed"))
                    return
                if frame.ftype == rs.FT_KEEPALIVE:
                    if frame.flags & rs.FLAG_RESPOND:
                        self._writer.write(
                            rs.frame_stream(
                                rs.encode_keepalive(
                                    frame.last_position, respond=False
                                )
                            )
                        )
                    continue
                if frame.ftype == rs.FT_ERROR and frame.stream_id == 0:
                    self._fail_pending(
                        RocketError(
                            frame.error_message, code=frame.error_code
                        )
                    )
                    return
                fut = self._pending.pop(frame.stream_id, None)
                if fut is None or fut.done():
                    continue
                if frame.ftype == rs.FT_PAYLOAD:
                    fut.set_result(frame)
                elif frame.ftype == rs.FT_ERROR:
                    fut.set_exception(
                        RocketError(
                            frame.error_message, code=frame.error_code
                        )
                    )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — fail callers, not the loop
            self._fail_pending(RocketError(f"rocket pump failed: {e}"))

    async def request_response(
        self,
        name: str,
        data: bytes,
        *,
        timeout_s: float = 30.0,
        other_metadata: Optional[Dict[str, str]] = None,
    ) -> RocketResponse:
        """One thrift call: returns the decoded ResponseRpcMetadata and
        the raw result-struct bytes; raises RocketError on transport or
        app-unknown errors (declared exceptions are returned — the
        caller holds the result spec needed to decode them)."""
        if self._dead is not None:
            raise RocketError(f"rocket connection dead: {self._dead}")
        sid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[sid] = fut
        md = encode_request_metadata(
            name,
            RPC_SINGLE_REQUEST_SINGLE_RESPONSE,
            client_timeout_ms=int(timeout_s * 1000),
            other=other_metadata,
        )
        self._writer.write(
            rs.frame_stream(rs.encode_request_response(sid, md, data))
        )
        await self._writer.drain()
        try:
            frame: rs.Frame = await asyncio.wait_for(fut, timeout_s)
        finally:
            self._pending.pop(sid, None)
        try:
            rmeta = (
                decode_struct(RESPONSE_RPC_METADATA, frame.metadata)
                if frame.metadata
                else {}
            )
        except ValueError as e:
            raise RocketCodecError(
                f"malformed response metadata for {name!r}: {e}"
            ) from e
        return RocketResponse(metadata=rmeta, data=frame.data)

    async def fire_and_forget(self, name: str, data: bytes) -> None:
        if self._dead is not None:
            raise RocketError(f"rocket connection dead: {self._dead}")
        sid = next(self._ids)
        md = encode_request_metadata(name, RPC_SINGLE_REQUEST_NO_RESPONSE)
        self._writer.write(
            rs.frame_stream(rs.encode_request_fnf(sid, md, data))
        )
        await self._writer.drain()


#: server dispatch: async (method name, args bytes, peer) -> (response
#: metadata bytes, result bytes) — the ctrl adapter builds both so the
#: transport stays IDL-agnostic
RocketDispatch = Callable[
    [str, bytes, object], Awaitable[Tuple[bytes, bytes]]
]


class RocketServer:
    """Serves fbthrift-rocket request-response on a TCP port.

    Validates the fbthrift SETUP handshake (protocol key), echoes
    KEEPALIVEs, runs each request concurrently, and maps dispatch
    failures to RSocket ERROR frames.  Streams (REQUEST_STREAM) get a
    REJECTED error — the reference CLI only needs request-response for
    the adapted method surface."""

    def __init__(
        self,
        dispatch: RocketDispatch,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        ssl=None,
    ):
        self.dispatch = dispatch
        self.host = host
        self.port = port
        self._ssl = ssl
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()

    async def start(self) -> "RocketServer":
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, ssl=self._ssl
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        from openr_tpu.common.net import stop_stream_server

        await stop_stream_server(self._server, self._conn_tasks)

    async def _on_connection(self, reader, writer) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        inflight: set = set()
        write_lock = asyncio.Lock()

        async def send(frame: bytes) -> None:
            async with write_lock:
                writer.write(rs.frame_stream(frame))
                await writer.drain()

        try:
            # handshake: first frame must be a valid fbthrift SETUP
            first = await rs.read_stream_frame(reader)
            if first is None:
                return
            if first.ftype != rs.FT_SETUP:
                await send(
                    rs.encode_error(
                        0, rs.ERR_INVALID_SETUP, "expected SETUP frame"
                    )
                )
                return
            try:
                decode_setup_metadata(first.metadata or b"")
            except ValueError as e:
                await send(rs.encode_error(0, rs.ERR_INVALID_SETUP, str(e)))
                return
            while True:
                frame = await rs.read_stream_frame(reader)
                if frame is None:
                    return
                if frame.ftype == rs.FT_KEEPALIVE:
                    if frame.flags & rs.FLAG_RESPOND:
                        await send(
                            rs.encode_keepalive(
                                frame.last_position, respond=False
                            )
                        )
                elif frame.ftype in (
                    rs.FT_REQUEST_RESPONSE,
                    rs.FT_REQUEST_FNF,
                ):
                    t = asyncio.create_task(
                        self._serve_request(frame, send, writer)
                    )
                    # tag at CREATE time: a CANCEL already buffered in
                    # the same TCP segment is processed before the task
                    # first runs, and must still find its stream id
                    t.rocket_sid = frame.stream_id  # type: ignore[attr-defined]
                    inflight.add(t)
                    t.add_done_callback(inflight.discard)
                elif frame.ftype == rs.FT_REQUEST_STREAM:
                    await send(
                        rs.encode_error(
                            frame.stream_id,
                            rs.ERR_REJECTED,
                            "streams not supported on this endpoint",
                        )
                    )
                elif frame.ftype == rs.FT_CANCEL:
                    for t in inflight:
                        if getattr(t, "rocket_sid", None) == frame.stream_id:
                            t.cancel()
                # METADATA_PUSH / others: ignorable per spec
        except ValueError as e:
            try:
                await send(rs.encode_error(0, rs.ERR_CONNECTION_ERROR, str(e)))
            except (ConnectionError, OSError):
                pass
        finally:
            for t in list(inflight):
                t.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._conn_tasks.discard(task)

    async def _serve_request(self, frame: rs.Frame, send, writer) -> None:
        try:
            if not frame.metadata:
                raise ValueError("request carries no RequestRpcMetadata")
            req = decode_struct(REQUEST_RPC_METADATA, frame.metadata)
            name = req.get("name") or ""
            peer = writer.get_extra_info("peername")
            rmeta, result = await self.dispatch(name, frame.data, peer)
            if frame.ftype == rs.FT_REQUEST_RESPONSE:
                await send(
                    rs.encode_payload(
                        frame.stream_id,
                        rmeta,
                        result,
                        complete=True,
                        next_=True,
                    )
                )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — surface as rsocket error
            LOG.warning("rocket request failed: %s", e)
            if frame.ftype == rs.FT_REQUEST_RESPONSE:
                try:
                    await send(
                        rs.encode_error(
                            frame.stream_id,
                            rs.ERR_APPLICATION_ERROR,
                            str(e),
                        )
                    )
                except (ConnectionError, OSError):
                    pass
