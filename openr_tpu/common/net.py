"""Shared asyncio server plumbing."""

from __future__ import annotations

import asyncio
from typing import Iterable, Optional


async def stop_stream_server(
    server: Optional[asyncio.base_events.Server],
    conn_tasks: Iterable[asyncio.Task],
) -> None:
    """Shut down an asyncio stream server: close the listener, cancel
    connection handlers, THEN await wait_closed().

    The ordering is load-bearing: since py3.12 ``wait_closed()`` blocks
    until every connection handler returns, so awaiting it while
    handlers are parked in reads (live KvStore peer sessions, idle
    operator connections) deadlocks shutdown."""
    if server is not None:
        server.close()
    tasks = list(conn_tasks)
    for t in tasks:
        t.cancel()
    for t in tasks:
        try:
            await t
        except (asyncio.CancelledError, Exception):  # noqa: BLE001
            pass
    if server is not None:
        await server.wait_closed()
