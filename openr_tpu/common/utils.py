"""Timing utilities: exponential backoff, throttle, debounce, step detector.

Semantic equivalents of the reference's common/ExponentialBackoff.h,
AsyncThrottle.h, AsyncDebounce.h, StepDetector.h, adapted to the clock-driven
asyncio runtime (all sleeping goes through `Clock` so tests can run in
virtual time).
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
from typing import Callable, Deque, Optional, Tuple

from openr_tpu.common.runtime import Actor, Clock


class ExponentialBackoff:
    """Doubling retry backoff (reference: common/ExponentialBackoff.h).

    reportError() doubles the current backoff starting from `initial` up to
    `maximum`; reportSuccess() clears it.  Time comes from the shared clock.
    """

    def __init__(self, initial: float, maximum: float, clock: Clock) -> None:
        assert initial > 0 and maximum >= initial
        self._initial = initial
        self._max = maximum
        self._clock = clock
        self._current = 0.0
        self._last_error_time = 0.0

    def can_try_now(self) -> bool:
        return self.time_remaining_until_retry() <= 0

    def report_success(self) -> None:
        self._current = 0.0

    def report_error(self) -> None:
        self._last_error_time = self._clock.now()
        if self._current == 0.0:
            self._current = self._initial
        else:
            self._current = min(self._current * 2, self._max)

    def report_status(self, ok: bool) -> None:
        self.report_success() if ok else self.report_error()

    def at_max_backoff(self) -> bool:
        return self._current >= self._max

    def get_current_backoff(self) -> float:
        return self._current

    def time_remaining_until_retry(self) -> float:
        if self._current == 0.0:
            return 0.0
        return max(0.0, self._last_error_time + self._current - self._clock.now())


class AsyncThrottle:
    """Coalesce rapid invocations: `callback` runs at most once per `timeout`
    window (reference: common/AsyncThrottle.h).

    First call schedules the callback `timeout` later; calls while scheduled
    are no-ops.
    """

    def __init__(
        self, actor: Actor, timeout: float, callback: Callable[[], object]
    ) -> None:
        self._actor = actor
        self._timeout = timeout
        self._callback = callback
        self._scheduled: Optional[asyncio.Task] = None

    def __call__(self) -> None:
        if self.is_active():
            return
        self._scheduled = self._actor.schedule(self._timeout, self._fire)

    def _fire(self):
        self._scheduled = None
        return self._callback()

    def is_active(self) -> bool:
        return self._scheduled is not None and not self._scheduled.done()

    def cancel(self) -> None:
        if self._scheduled is not None:
            self._scheduled.cancel()
            self._scheduled = None


class AsyncDebounce:
    """Debounce with exponential hold-off (reference: common/AsyncDebounce.h).

    Every invocation doubles the pending wait (min → max) and *reschedules*
    the callback; once the timer fires, the backoff resets.  Used by Decision
    for the 10–250 ms SPF rebuild window (Decision.cpp:114-120).
    """

    def __init__(
        self,
        actor: Actor,
        min_backoff: float,
        max_backoff: float,
        callback: Callable[[], object],
    ) -> None:
        self._actor = actor
        self._backoff = ExponentialBackoff(min_backoff, max_backoff, actor.clock)
        self._callback = callback
        self._scheduled: Optional[asyncio.Task] = None
        self._deadline = 0.0

    def __call__(self) -> None:
        if not self._backoff.at_max_backoff():
            self._backoff.report_error()
            self._reschedule(self._backoff.get_current_backoff())
        assert self.is_scheduled()

    def _reschedule(self, delay: float) -> None:
        if self._scheduled is not None:
            self._scheduled.cancel()
        self._deadline = self._actor.clock.now() + delay
        self._scheduled = self._actor.schedule(delay, self._fire)

    def _fire(self):
        self._scheduled = None
        self._backoff.report_success()
        return self._callback()

    def is_scheduled(self) -> bool:
        return self._scheduled is not None and not self._scheduled.done()

    def cancel_scheduled_timeout(self) -> None:
        if self._scheduled is not None:
            self._scheduled.cancel()
            self._scheduled = None
        self._backoff.report_success()


class SlidingWindowAvg:
    """Fixed-count sliding-window average (stand-in for
    folly::BucketedTimeSeries as used by StepDetector)."""

    def __init__(self, max_count: int) -> None:
        self._max = max_count
        self._vals: Deque[float] = collections.deque(maxlen=max_count)

    def add(self, v: float) -> None:
        self._vals.append(v)

    def avg(self) -> float:
        if not self._vals:
            return 0.0
        return sum(self._vals) / len(self._vals)

    def count(self) -> int:
        return len(self._vals)


class StepDetector:
    """Detect steps in a noisy time series (RTT) — fast vs slow sliding
    window means with rising/falling-edge hysteresis plus an absolute
    threshold for staircase drift (reference: common/StepDetector.h).

    Used by Spark to report neighbor RTT changes only when meaningful
    (Spark.h:327).
    """

    def __init__(
        self,
        step_cb: Callable[[float], None],
        fast_window_size: int = 10,
        slow_window_size: int = 60,
        lower_threshold_pct: float = 2.0,
        upper_threshold_pct: float = 5.0,
        abs_threshold: float = 500.0,
    ) -> None:
        assert lower_threshold_pct < upper_threshold_pct
        assert fast_window_size < slow_window_size
        self._fast = SlidingWindowAvg(fast_window_size)
        self._slow = SlidingWindowAvg(slow_window_size)
        self._slow_size = slow_window_size
        self._lo = lower_threshold_pct
        self._hi = upper_threshold_pct
        self._abs = abs_threshold
        self._cb = step_cb
        self._in_transit = False
        self._last_avg = 0.0
        self._last_avg_init = False

    def add_value(self, val: float) -> None:
        self._fast.add(val)
        self._slow.add(val)
        fast_avg = self._fast.avg()
        slow_avg = self._slow.avg()

        if not self._last_avg_init and self._slow.count() >= self._slow_size // 2:
            self._last_avg = slow_avg
            self._last_avg_init = True

        if slow_avg == 0:
            raise ZeroDivisionError("slow window average is zero")
        diff = abs((fast_avg - slow_avg) / slow_avg) * 100

        if self._in_transit:
            if diff <= self._lo:
                # falling edge: step complete, fast mean is the new level
                self._in_transit = False
                self._cb(fast_avg)
                self._last_avg = fast_avg
                self._last_avg_init = True
                return
        elif diff >= self._hi:
            self._in_transit = True

        # gradual drift missed by the edge detector
        if (
            diff <= self._lo
            and self._last_avg_init
            and abs(slow_avg - self._last_avg) >= self._abs
        ):
            self._cb(slow_avg)
            self._last_avg = slow_avg


def sanitize_name(name: str) -> str:
    """Counter-key-safe node/area names."""
    return name.replace(".", "_").replace("/", "_")


class Throttle2Tuple:
    """Helper: (initial, max) seconds pair for config plumbing."""

    def __init__(self, pair: Tuple[float, float]):
        self.initial, self.max = pair


@contextlib.contextmanager
def gc_paused():
    """Pause the cyclic collector for a large-allocation section.

    Bulk LSDB ingest and full route builds allocate a few container
    objects per advertisement/route; CPython gen-2 collections re-scan
    the ever-growing LSDB+RIB heap mid-batch (measured 2x ingest cost
    at 409,600 prefixes).  No-op when GC is already disabled."""
    import gc

    was_enabled = gc.isenabled()
    if was_enabled:
        gc.disable()
    try:
        yield
    finally:
        if was_enabled:
            gc.enable()
