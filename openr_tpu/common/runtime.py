"""Actor runtime — the OpenrEventBase equivalent.

The reference gives every module its own thread + folly::EventBase +
FiberManager (openr/common/OpenrEventBase.h:28); modules talk only through
queues.  Here every module is an `Actor` owning asyncio tasks ("fibers") on a
shared event loop, talking only through `openr_tpu.messaging` queues — same
single-writer discipline, no shared mutable state.

Time is pluggable: `WallClock` for production, `SimClock` for deterministic
discrete-event tests (the reference's timer-heavy FSM tests are wall-clock
and slow; ours run in virtual time, mirroring the determinism goal of
MockIoProvider-based testing, tests/mocks/MockIoProvider.h).
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time
from typing import Any, Callable, Coroutine, Dict, List, Optional


class Clock:
    """Time source. All protocol-plane sleeping/timing MUST go through this."""

    def now(self) -> float:
        raise NotImplementedError

    def now_ms(self) -> int:
        return int(self.now() * 1000)

    async def sleep(self, delay: float) -> None:
        raise NotImplementedError

    def mark_observer(self, label: str) -> None:
        """Declare the fiber named `label` an OBSERVER: a sampler that
        reads cross-module state without feeding the protocol plane
        (monitoring sweeps).  On SimClock, observer wakeups dispatch
        after every same-instant mutator wakeup, so what a sampler sees
        at virtual time T is the settled post-T state on EVERY legal
        schedule — not whichever side of a same-tick race the dispatch
        order happened to land on.  No-op on wall clocks, where ties
        have no deterministic order to begin with."""

    def mark_prologue(self, label: str) -> None:
        """Declare the fiber named `label` a PROLOGUE: an environment
        driver (fault injection) whose effects at virtual time T must
        apply to ALL of tick T.  On SimClock, prologue wakeups dispatch
        before every same-instant mutator wakeup — a fault injected at T
        covers a packet sent at T on every legal schedule, never "did
        the fault fiber happen to run first".  No-op on wall clocks."""


class WallClock(Clock):
    def now(self) -> float:
        return time.monotonic()  # orlint: disable=clock-now (WallClock IS the Clock everyone routes through)

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(max(0.0, delay))  # orlint: disable=clock-sleep (WallClock IS the Clock everyone routes through)


class SimClock(Clock):
    """Deterministic discrete-event virtual clock.

    Tasks `await clock.sleep(dt)`; a test driver calls `await run_for(dt)` /
    `await run_until(t)` which advances virtual time event by event, letting
    the loop quiesce between events.  Any real work (queue handoffs, FSM
    transitions) happens during the quiesce rounds, so test outcomes are
    independent of host scheduling.
    """

    def __init__(self, start: float = 0.0) -> None:
        self._now = start
        self._heap: List = []
        self._seq = itertools.count()
        self.activity = 0  # bumped by sleepers waking; used for quiescing
        #: optional schedule perturber (openr_tpu.chaos.schedule): when
        #: installed, same-instant wakeups dispatch in a seeded-permuted
        #: order instead of FIFO registration order — the race detector's
        #: lever.  None = canonical schedule, byte-for-byte as before.
        self._perturber = None
        #: fiber labels whose wakeups defer past every same-instant
        #: mutator wakeup (Clock.mark_observer)
        self._observer_labels: set = set()
        #: fiber labels whose wakeups precede every same-instant mutator
        #: wakeup (Clock.mark_prologue)
        self._prologue_labels: set = set()

    def set_perturber(self, perturber) -> None:
        self._perturber = perturber

    def mark_observer(self, label: str) -> None:
        self._observer_labels.add(label)

    def mark_prologue(self, label: str) -> None:
        self._prologue_labels.add(label)

    def now(self) -> float:
        return self._now

    async def sleep(self, delay: float) -> None:
        if delay <= 0:
            await asyncio.sleep(0)
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        task = asyncio.current_task()
        label = task.get_name() if task is not None else ""
        heapq.heappush(
            self._heap, (self._now + delay, next(self._seq), label, fut)
        )
        await fut

    async def _settle(self) -> None:
        # Let the asyncio ready-queue drain: plain yields until chained
        # callbacks stop producing new ones.  Queue handoffs resolve futures
        # synchronously, so a bounded number of yields reaches quiescence.
        for _ in range(3):
            before = self.activity
            for _ in range(10):
                await asyncio.sleep(0)
            if self.activity == before:
                return

    async def run_until(self, deadline: float) -> None:
        await self._settle()
        while self._heap and self._heap[0][0] <= deadline:
            # All wakeups due at the same virtual instant form one batch:
            # mutators dispatch first (registration order canonically,
            # seeded-permuted under a perturber); observer-labelled fibers
            # (mark_observer) defer until no mutator wakeup remains at
            # this instant, so a monitoring sweep at T samples the settled
            # post-T state on every legal schedule.  Fibers re-arming at
            # the same instant join the next batch before time advances.
            t0 = self._heap[0][0]
            self._now = max(self._now, t0)
            observers: List = []
            while True:
                prologue: List = []
                batch: List = []
                while self._heap and self._heap[0][0] == t0:
                    entry = heapq.heappop(self._heap)
                    if entry[2] in self._observer_labels:
                        observers.append(entry)
                    elif entry[2] in self._prologue_labels:
                        prologue.append(entry)
                    else:
                        batch.append(entry)
                if not prologue and not batch:
                    break
                # prologue fibers (fault injectors) run first, label-
                # ordered and unperturbed — their effects at t0 cover
                # every mutator wakeup at t0 on every legal schedule
                prologue.sort(key=lambda e: e[2])
                await self._dispatch(prologue, perturb=False)
                await self._dispatch(batch)
            # Observers dispatch label-ordered and are NEVER perturbed:
            # their relative order vs mutators is pinned (after), and
            # label order pins sampler-vs-sampler (a health sweep never
            # sees this tick's watchdog crash on any schedule).
            observers.sort(key=lambda e: e[2])
            await self._dispatch(observers, perturb=False)
        self._now = max(self._now, deadline)
        await self._settle()

    async def _dispatch(self, batch: List, perturb: bool = True) -> None:
        """Wake one batch, one settle round per wakeup (same cadence as
        the original single-pop dispatch)."""
        if perturb and self._perturber is not None:
            batch = self._perturber.order_wakeups(batch)
        for t, _, label, fut in batch:
            if not fut.done():
                self.activity += 1
                if self._perturber is not None:
                    self._perturber.note_turn(t, label)
                fut.set_result(None)
            await self._settle()

    async def run_for(self, duration: float) -> None:
        await self.run_until(self._now + duration)

    def pending_timers(self) -> int:
        return sum(1 for _, _, _, f in self._heap if not f.done())


# ---------------------------------------------------------------------------
# fb303-style counters (reference: fb303 ServiceData, used by every module)
# ---------------------------------------------------------------------------


class Histogram:
    """Fixed-bucket geometric latency histogram (HdrHistogram-flavored).

    Bucket 0 covers [0, min_bound]; bucket i covers (edge(i-1), edge(i)]
    with edge(i) = min_bound * growth**i; one overflow bucket absorbs
    values beyond the last edge.  The default config spans 0.01 ms to
    ~12 h in 160 buckets (~15% relative error per bucket), matching the
    fb303 EXPORT_HISTOGRAM role: cheap O(1) observe on the hot path,
    percentile estimates via in-bucket linear interpolation.
    Two histograms with identical (min_bound, growth, buckets) merge by
    bucket-count addition (cross-node aggregation in bench/emulation).
    """

    __slots__ = (
        "min_bound", "growth", "edges", "counts",
        "count", "total", "vmin", "vmax",
    )

    def __init__(
        self,
        min_bound: float = 0.01,
        growth: float = 1.15,
        num_buckets: int = 160,
    ) -> None:
        self.min_bound = float(min_bound)
        self.growth = float(growth)
        #: edges[i] == inclusive UPPER bound of bucket i
        self.edges: List[float] = [
            self.min_bound * self.growth ** i for i in range(num_buckets)
        ]
        #: one count per edge bucket + one overflow bucket
        self.counts: List[int] = [0] * (num_buckets + 1)
        self.count = 0
        self.total = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None

    def bucket_index(self, value: float) -> int:
        """First bucket whose upper edge is >= value (overflow = last)."""
        import bisect

        if value <= self.min_bound:
            return 0
        return bisect.bisect_left(self.edges, value)

    def bucket_bounds(self, i: int) -> tuple:
        """(lower_exclusive, upper_inclusive) of bucket i; the overflow
        bucket's upper bound is the observed max (inf when empty)."""
        lo = 0.0 if i == 0 else self.edges[i - 1]
        if i < len(self.edges):
            return lo, self.edges[i]
        return lo, self.vmax if self.vmax is not None else float("inf")

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[self.bucket_index(v)] += 1
        self.count += 1
        self.total += v
        if self.vmin is None or v < self.vmin:
            self.vmin = v
        if self.vmax is None or v > self.vmax:
            self.vmax = v

    def percentile(self, pct: float) -> Optional[float]:
        """Estimated value at `pct` (0-100): linear interpolation within
        the containing bucket, clamped to the observed [min, max] so
        single-valued populations report exactly that value.  An EMPTY
        histogram has no percentiles by definition — every rank returns
        None (never 0.0, which would read as a real latency), and
        `percentiles()` returns a dict of Nones; consumers render them
        as absent (breeze prints "-", the Prometheus exposition emits
        only the zero `_count`)."""
        if self.count == 0:
            return None
        rank = (pct / 100.0) * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo, hi = self.bucket_bounds(i)
                frac = min(max((rank - cum) / c, 0.0), 1.0)
                v = lo + (hi - lo) * frac
                return min(max(v, self.vmin), self.vmax)
            cum += c
        return self.vmax

    def percentiles(self, pcts=(50, 95, 99)) -> Dict[str, Optional[float]]:
        return {f"p{g:g}": self.percentile(g) for g in pcts}

    def merge(self, other: "Histogram") -> "Histogram":
        """In-place bucket-count addition.

        Same (min_bound, growth) but DIFFERENT bucket counts merge by
        widening self to the larger width: the geometric edges of the
        narrower histogram are a prefix of the wider one's, so regular
        buckets add positionally, and the narrower histogram's overflow
        count lands in the merged OVERFLOW bucket (conservative — those
        samples may truly belong in one of the newly-exposed upper
        buckets, but the narrow histogram no longer knows; count/sum/
        min/max stay exact either way).  Differing (min_bound, growth)
        still raises ValueError: the edge grids are incompatible and a
        positional add would silently mis-bin every sample."""
        if self.min_bound != other.min_bound or self.growth != other.growth:
            raise ValueError("histogram configs differ; cannot merge")
        if len(self.counts) < len(other.counts):
            grow = len(other.counts) - len(self.counts)
            self.edges.extend(
                self.min_bound * self.growth ** i
                for i in range(len(self.edges), len(other.edges))
            )
            overflow = self.counts.pop()
            self.counts.extend([0] * grow)
            self.counts.append(overflow)
        for i, c in enumerate(other.counts[:-1]):
            self.counts[i] += c
        self.counts[-1] += other.counts[-1]
        self.count += other.count
        self.total += other.total
        for v in (other.vmin, other.vmax):
            if v is None:
                continue
            if self.vmin is None or v < self.vmin:
                self.vmin = v
            if self.vmax is None or v > self.vmax:
                self.vmax = v
        return self

    def copy(self) -> "Histogram":
        h = Histogram(self.min_bound, self.growth, len(self.edges))
        h.counts = list(self.counts)
        h.count, h.total = self.count, self.total
        h.vmin, h.vmax = self.vmin, self.vmax
        return h

    def snapshot(self) -> Dict[str, Any]:
        """The ctrl-API / breeze wire form."""
        out: Dict[str, Any] = {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin,
            "max": self.vmax,
        }
        out.update(self.percentiles())
        return out

    def config(self) -> Dict[str, Any]:
        """Bucket-grid identity — two histograms merge iff these match
        (up to width, see `merge`)."""
        return {
            "min_bound": self.min_bound,
            "growth": self.growth,
            "num_buckets": len(self.edges),
        }

    def bucket_items(self) -> List[tuple]:
        """Nonzero ``(upper_edge_inclusive, count)`` pairs in edge
        order; the overflow bucket reports ``inf``.  The compact form
        the metrics-export tier serializes (160 mostly-zero buckets per
        key would dominate every snapshot line)."""
        out: List[tuple] = []
        for i, c in enumerate(self.counts):
            if c:
                out.append(
                    (
                        self.edges[i] if i < len(self.edges) else float("inf"),
                        c,
                    )
                )
        return out


class CounterMap:
    """Flat counter namespace; `dump()` feeds the ctrl API `getCounters`.
    Also hosts the histogram namespace (`observe`/`percentiles`) backing
    the ctrl API `getHistograms` — latency distributions live next to the
    gauges they explain."""

    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}

    def bump(self, key: str, delta: float = 1) -> None:
        self._counters[key] = self._counters.get(key, 0) + delta

    def set(self, key: str, value: float) -> None:
        self._counters[key] = value

    def get(self, key: str) -> float:
        return self._counters.get(key, 0)

    def dump(self, prefix: str = "") -> Dict[str, float]:
        if not prefix:
            return dict(self._counters)
        return {k: v for k, v in self._counters.items() if k.startswith(prefix)}

    # -- histograms --------------------------------------------------------

    def observe(self, key: str, value: float) -> None:
        """Record one sample into the named histogram (created on first
        observe with the default bucket config)."""
        h = self._histograms.get(key)
        if h is None:
            h = self._histograms[key] = Histogram()
        h.observe(value)

    def histogram(self, key: str) -> Optional[Histogram]:
        return self._histograms.get(key)

    def histogram_keys(self) -> List[str]:
        return sorted(self._histograms)

    def percentiles(self, key: str, pcts=(50, 95, 99)):
        """{"p50": .., "p95": .., "p99": ..} or None when never observed."""
        h = self._histograms.get(key)
        return None if h is None else h.percentiles(pcts)

    def dump_histograms(self, prefix: str = "") -> Dict[str, Dict]:
        return {
            k: h.snapshot()
            for k, h in self._histograms.items()
            if not prefix or k.startswith(prefix)
        }

    def clear(self) -> None:
        self._counters.clear()
        self._histograms.clear()


class Actor:
    """A protocol-plane module: a set of cooperating asyncio tasks with a
    shared clock, counters, and an ordered stop.

    Subclasses override `run()` (main fiber) and may `spawn()` more fibers.
    Matches the reference's module lifecycle: constructed with its queues,
    started on its own execution context, stopped by closing queues then
    awaiting the tasks (openr/Main.cpp:231-470, 498-541).
    """

    def __init__(self, name: str, clock: Clock, counters: Optional[CounterMap] = None):
        self.name = name
        self.clock = clock
        self.counters = counters if counters is not None else CounterMap()
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        self._fiber_failed = False
        self.last_heartbeat: float = clock.now()

    # -- lifecycle ---------------------------------------------------------

    async def run(self) -> None:  # pragma: no cover - default no-op
        return

    def start(self) -> None:
        self.spawn(self._run_wrapper(), name=f"{self.name}.main")

    async def _run_wrapper(self) -> None:
        try:
            await self.run()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 - module crash is fatal in reference
            import traceback

            traceback.print_exc()
            self.counters.bump(f"{self.name}.crash")
            raise

    def spawn(self, coro: Coroutine, name: str = "") -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(
            coro, name=name or f"{self.name}.fiber{len(self._tasks)}"
        )
        self._tasks.append(task)
        # Prune on completion: timer-heavy modules (throttle/debounce) spawn
        # constantly; a long-lived daemon must not accumulate dead tasks.
        task.add_done_callback(self._discard_task)
        return task

    def _discard_task(self, task: asyncio.Task) -> None:
        try:
            self._tasks.remove(task)
        except ValueError:
            pass
        if not task.cancelled() and task.exception() is not None:
            # Surface module-fiber crashes rather than swallowing them; the
            # Watchdog stops refreshing this actor's heartbeat and fires.
            self.counters.bump(f"{self.name}.fiber_exception")
            self._fiber_failed = True

    def spawn_queue_loop(self, rqueue, handler: Callable, name: str = "") -> asyncio.Task:
        """The canonical module fiber: drain a queue until close
        (reference pattern: `while (true) { auto maybe = q.get(); ... }`)."""

        async def _loop():
            from openr_tpu.messaging.queue import QueueClosedError

            try:
                while True:
                    item = await rqueue.get()
                    self.touch()
                    r = handler(item)
                    if asyncio.iscoroutine(r):
                        await r
            except QueueClosedError:
                return

        return self.spawn(_loop(), name=name or f"{self.name}.qloop")

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        tasks = list(self._tasks)  # done-callbacks mutate the live list
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks.clear()

    # -- watchdog support --------------------------------------------------

    def touch(self) -> None:
        self.last_heartbeat = self.clock.now()

    @property
    def fiber_failed(self) -> bool:
        """True once any fiber died with an exception — the Watchdog
        crashes the daemon promptly on this (watchdog.py)."""
        return self._fiber_failed

    @property
    def healthy(self) -> bool:
        """No fiber has died with an exception and the actor is running.
        The Watchdog refreshes heartbeats of healthy actors (the asyncio
        analogue of the reference's evb no-op timer, Watchdog.cpp:71-98) so
        an idle-but-alive module never reads as stalled."""
        return not self._fiber_failed and not self._stopped

    def schedule(self, delay: float, fn: Callable[[], Any], name: str = "") -> asyncio.Task:
        """One-shot timer (OpenrEventBase::scheduleTimeout equivalent)."""

        async def _timer():
            await self.clock.sleep(delay)
            r = fn()
            if asyncio.iscoroutine(r):
                await r

        return self.spawn(_timer(), name=name or f"{self.name}.timer")
