"""Build-on-demand loader for the repo's native C++ libraries.

The reference ships its native layer (openr/nl, platform) as CMake-built
C++; here each native component is a single translation unit under
`native/` compiled lazily into a shared object next to its source.  A
rebuild happens when the source is newer than the cached .so (mtime), under
an exclusive file lock so parallel test workers don't race the compiler.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
from pathlib import Path
from typing import List, Optional

NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"

_CXX = os.environ.get("CXX", "g++")
_CXXFLAGS = ["-O2", "-g", "-fPIC", "-shared", "-std=c++17", "-Wall"]


class NativeBuildError(RuntimeError):
    pass


def build_native_lib(name: str, extra_flags: Optional[List[str]] = None) -> Path:
    """Compile native/<name>.cc -> native/lib<name>.so if stale; return path."""
    src = NATIVE_DIR / f"{name}.cc"
    out = NATIVE_DIR / f"lib{name}.so"
    if not src.exists():
        raise NativeBuildError(f"missing native source {src}")
    lock_path = NATIVE_DIR / f".{name}.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
            return out
        tmp = out.with_suffix(".so.tmp")
        cmd = [_CXX, *_CXXFLAGS, *(extra_flags or []), str(src), "-o", str(tmp)]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
            )
        os.replace(tmp, out)
    return out


def load_native_lib(name: str) -> ctypes.CDLL:
    return ctypes.CDLL(str(build_native_lib(name)))
