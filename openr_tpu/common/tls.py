"""TLS for the ctrl server and KvStore peer RPC plane.

Reference parity: the reference serves thrift over TLS via wangle/fizz
(/root/reference/openr/Main.cpp:399-416) with cert/key/CA paths from
gflags (/root/reference/openr/common/Flags.cpp:10-37) and verifies peers
against an acceptable-peer-name list.  Here:

  * ``TlsConfig`` lives on OpenrConfig; cert/key/CA are PEM file paths
  * the ctrl server wraps its listener with ``server_ssl_context`` —
    which also secures KvStore peer sessions, since TcpKvStoreTransport
    rides the ctrl RPC plane (kvstore/transport.py)
  * mutual auth: ``require_client_cert`` makes the server demand and
    verify a client cert against the CA (the reference's mTLS shape —
    peers are authenticated by CA chain, not hostname, so hostname
    checking is off by default like wangle's SSLVerifyPeerEnforce)
  * plaintext fallback: ``enabled=False`` (the default) keeps every
    plane on plaintext TCP — the reference's ``enable_secure_thrift``
    off state; when enabled but cert files are missing, the default
    ``strict=True`` refuses to start (fail closed, like wangle/fizz);
    ``strict=False`` must be opted into explicitly to log-and-fall-back
    for lab/dev bringup.  Servers export a ``ctrl.tls_active`` counter so a
    downgrade is observable, not just one log line.

Test certs are generated with the ``cryptography`` package (see
tests/test_tls.py); ops deployments bring their own PEMs.
"""

from __future__ import annotations

import logging
import os
import ssl
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)


@dataclass
class TlsConfig:
    """Secure-transport knobs (reference: Flags.cpp:10-37 cert flags +
    OpenrConfig.thrift ThriftServer config)."""

    enabled: bool = False
    cert_path: str = ""
    key_path: str = ""
    #: CA bundle used BOTH to verify peers (server side, when
    #: require_client_cert) and servers (client side)
    ca_path: str = ""
    #: mutual auth: server demands a client certificate signed by ca_path
    require_client_cert: bool = True
    #: verify the server certificate on the client side (CA chain)
    verify_server: bool = True
    #: check the server cert's hostname/SAN — off by default: infra mTLS
    #: authenticates by CA, and nodes dial link-local/loopback addresses
    #: that never match SANs
    verify_hostname: bool = False
    #: refuse to start when enabled but certs are unusable.  Defaults to
    #: FAIL CLOSED: with tls.enabled a typo'd cert path must not
    #: silently downgrade the plane carrying drain/set-key mutations and
    #: the whole LSDB sync to plaintext (the reference's wangle/fizz
    #: server likewise refuses to start).  Set strict=False explicitly
    #: for lab bringup where plaintext fallback is acceptable.
    strict: bool = True

    def _files_ok(self, role: str) -> bool:
        if role == "server":
            need = [self.cert_path, self.key_path]
            if self.require_client_cert:
                need.append(self.ca_path)
        else:  # client: cert/key optional (mTLS), CA only when verifying
            need = []
            if self.verify_server:
                need.append(self.ca_path)
            if self.cert_path or self.key_path:
                need += [self.cert_path, self.key_path]
        return all(p and os.path.exists(p) for p in need)


def server_ssl_context(tls: Optional[TlsConfig]) -> Optional[ssl.SSLContext]:
    """SSLContext for the ctrl listener; None = serve plaintext."""
    if tls is None or not tls.enabled:
        return None
    if not tls._files_ok("server"):
        if tls.strict:
            raise FileNotFoundError(
                f"tls enabled but cert/key/ca missing: cert={tls.cert_path!r} "
                f"key={tls.key_path!r} ca={tls.ca_path!r}"
            )
        log.warning("tls enabled but certs missing; falling back to plaintext")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(tls.cert_path, tls.key_path)
    if tls.require_client_cert:
        ctx.load_verify_locations(tls.ca_path)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(tls: Optional[TlsConfig]) -> Optional[ssl.SSLContext]:
    """SSLContext for dialing a TLS ctrl server; None = plaintext."""
    if tls is None or not tls.enabled:
        return None
    if not tls._files_ok("client"):
        if tls.strict:
            raise FileNotFoundError(
                f"tls enabled but cert/key/ca missing: cert={tls.cert_path!r} "
                f"key={tls.key_path!r} ca={tls.ca_path!r}"
            )
        log.warning("tls enabled but certs missing; dialing plaintext")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    if tls.verify_server:
        ctx.load_verify_locations(tls.ca_path)
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.check_hostname = tls.verify_hostname
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    # client cert for mutual auth (ignored by servers that don't ask)
    if tls.cert_path and tls.key_path:
        ctx.load_cert_chain(tls.cert_path, tls.key_path)
    return ctx
