"""openr_tpu.platform — kernel/platform I/O layer.

Reference parity: openr/platform (FibService agent over netlink) +
openr/nl (netlink protocol sockets).  The nl codec is native C++
(native/nl_codec.cc); see openr_tpu.platform.nl.
"""

from openr_tpu.platform.fib_service import (  # noqa: F401
    CLIENT_ID_OPENR,
    FibServiceServer,
    NetlinkFibAgent,
    NetlinkFibHandler,
    RemoteFibAgent,
)
