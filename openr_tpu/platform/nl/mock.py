"""Fake kernel for tests — MockNetlinkProtocolSocket + NetlinkEventsInjector.

Reference parity: openr/tests/mocks/MockNetlinkProtocolSocket.h and
NetlinkEventsInjector (link-monitor/tests): an in-memory links/addrs/routes
table implementing the same API as the real socket, with an injector that
fakes kernel events onto the netlinkEventsQueue.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.nl.codec import NlAddr, NlLink, NlRoute
from openr_tpu.platform.nl.nl_socket import BaseNetlinkProtocolSocket
from openr_tpu.types import InterfaceInfo


class MockNetlinkProtocolSocket(BaseNetlinkProtocolSocket):
    def __init__(self, events_queue: Optional[ReplicateQueue] = None) -> None:
        self.events_queue = events_queue
        self.links: Dict[int, NlLink] = {}
        self.addrs: Dict[Tuple[int, str], NlAddr] = {}
        self.routes: Dict[Tuple, NlRoute] = {}
        self.fail = False  # failure injection
        self.num_route_adds = 0
        self.num_route_dels = 0

    def _check(self) -> None:
        if self.fail:
            raise OSError("mock netlink failure injected")

    # -- route/addr ops ------------------------------------------------------

    async def add_route(self, route: NlRoute) -> None:
        self._check()
        self.routes[route.key()] = route
        self.num_route_adds += 1

    async def delete_route(self, route: NlRoute) -> None:
        self._check()
        self.routes.pop(route.key(), None)
        self.num_route_dels += 1

    async def add_if_address(self, if_index: int, prefix: str) -> None:
        self._check()
        self.addrs[(if_index, prefix)] = NlAddr(if_index=if_index, prefix=prefix)

    async def del_if_address(self, if_index: int, prefix: str) -> None:
        self._check()
        self.addrs.pop((if_index, prefix), None)

    # -- dumps ---------------------------------------------------------------

    async def get_all_links(self) -> List[NlLink]:
        self._check()
        return list(self.links.values())

    async def get_all_addrs(self) -> List[NlAddr]:
        self._check()
        return list(self.addrs.values())

    async def get_all_routes(
        self, protocol: Optional[int] = None
    ) -> List[NlRoute]:
        self._check()
        return [
            r
            for r in self.routes.values()
            if protocol is None or r.protocol == protocol
        ]


class NetlinkEventsInjector:
    """Drives the mock kernel: bring links up/down, add/remove addresses,
    publishing merged InterfaceInfo events exactly like the real socket."""

    def __init__(self, nl_sock: MockNetlinkProtocolSocket) -> None:
        self.nl = nl_sock

    def _publish(self, if_index: int) -> None:
        link = self.nl.links.get(if_index)
        if link is None or self.nl.events_queue is None:
            return
        networks = [
            a.prefix for (idx, _), a in self.nl.addrs.items() if idx == if_index
        ]
        self.nl.events_queue.push(
            InterfaceInfo(
                if_name=link.if_name,
                is_up=link.is_up,
                if_index=if_index,
                networks=networks,
            )
        )

    def set_link(self, if_index: int, if_name: str, is_up: bool) -> None:
        self.nl.links[if_index] = NlLink(
            if_index=if_index, if_name=if_name, is_up=is_up
        )
        self._publish(if_index)

    def add_address(self, if_index: int, prefix: str) -> None:
        self.nl.addrs[(if_index, prefix)] = NlAddr(
            if_index=if_index, prefix=prefix
        )
        self._publish(if_index)

    def del_address(self, if_index: int, prefix: str) -> None:
        self.nl.addrs.pop((if_index, prefix), None)
        self._publish(if_index)
