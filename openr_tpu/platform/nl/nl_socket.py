"""NetlinkProtocolSocket — async AF_NETLINK driver over the native codec.

Reference parity: openr/nl/NetlinkProtocolSocket.{h,cpp}
(NetlinkProtocolSocket.h:99): an async request queue with per-seq ack
tracking, kernel event subscription (link/addr/neigh groups) streamed to a
ReplicateQueue, and the bulk getters (getAllLinks/getAllRoutes/...).

The IPv6 replace quirk the reference handles
(NetlinkProtocolSocket.h:110-121) is handled the same way: the kernel does
not honor NLM_F_REPLACE for IPv6 multipath routes, so IPv6 updates are
delete-then-add while IPv4 uses atomic replace.

Interface events are merged into `InterfaceInfo` snapshots (the contract
LinkMonitor consumes on netlinkEventsQueue) on top of the raw NlLink/NlAddr
stream.
"""

from __future__ import annotations

import asyncio
import errno
import os
import socket as pysocket
import struct
from typing import Dict, List, Optional

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.nl.codec import (
    AF_INET,
    AF_INET6,
    AF_MPLS,
    NlAck,
    NlAddr,
    NlDone,
    NlLink,
    NlNeighbor,
    NlRoute,
    RTM_GETADDR,
    RTM_GETLINK,
    RTM_GETROUTE,
    get_codec,
)
from openr_tpu.types import InterfaceInfo

# rtnetlink multicast groups (linux/rtnetlink.h RTMGRP_*)
RTMGRP_LINK = 0x1
RTMGRP_NEIGH = 0x4
RTMGRP_IPV4_IFADDR = 0x10
RTMGRP_IPV6_IFADDR = 0x100

_EVENT_GROUPS = RTMGRP_LINK | RTMGRP_NEIGH | RTMGRP_IPV4_IFADDR | RTMGRP_IPV6_IFADDR

NETLINK_ROUTE = 0


class NetlinkSocketError(OSError):
    pass


class BaseNetlinkProtocolSocket:
    """API shared by the real socket and MockNetlinkProtocolSocket."""

    async def add_route(self, route: NlRoute) -> None:
        raise NotImplementedError

    async def delete_route(self, route: NlRoute) -> None:
        raise NotImplementedError

    async def add_if_address(self, if_index: int, prefix: str) -> None:
        raise NotImplementedError

    async def del_if_address(self, if_index: int, prefix: str) -> None:
        raise NotImplementedError

    async def get_all_links(self) -> List[NlLink]:
        raise NotImplementedError

    async def get_all_addrs(self) -> List[NlAddr]:
        raise NotImplementedError

    async def get_all_routes(
        self, protocol: Optional[int] = None
    ) -> List[NlRoute]:
        raise NotImplementedError

    async def get_all_interfaces(self) -> List[InterfaceInfo]:
        """Links + addrs merged, the LinkMonitor sync view."""
        links = await self.get_all_links()
        addrs = await self.get_all_addrs()
        by_index: Dict[int, InterfaceInfo] = {}
        for ln in links:
            if ln.is_del:
                continue
            by_index[ln.if_index] = InterfaceInfo(
                if_name=ln.if_name, is_up=ln.is_up, if_index=ln.if_index
            )
        for ad in addrs:
            info = by_index.get(ad.if_index)
            if info is not None and not ad.is_del:
                info.networks.append(ad.prefix)
        return list(by_index.values())

    def close(self) -> None:
        pass


class NetlinkProtocolSocket(BaseNetlinkProtocolSocket):
    """The real thing: one request socket (acks/dumps) + one event socket
    (multicast groups), both non-blocking on the running loop."""

    def __init__(
        self,
        events_queue: Optional[ReplicateQueue] = None,
        route_protocol: int = 99,
        neighbor_events_queue: Optional[ReplicateQueue] = None,
    ) -> None:
        self.codec = get_codec()
        self.events_queue = events_queue
        #: raw kernel neighbor-table events (NlNeighbor) — NeighborMonitor
        #: consumes these for address-unreachable fast teardown
        self.neighbor_events_queue = neighbor_events_queue
        self.route_protocol = route_protocol
        self._seq = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._dump_acc: Dict[int, List[object]] = {}
        #: one request at a time on the shared socket: overlapping kernel
        #: dumps fail with EBUSY, and serializing also makes the single
        #: open dump accumulator unambiguous for multi-part replies
        self._req_lock = asyncio.Lock()
        self._ifaces: Dict[int, InterfaceInfo] = {}
        self._started = False

        self._req = pysocket.socket(
            pysocket.AF_NETLINK, pysocket.SOCK_RAW, NETLINK_ROUTE
        )
        self._req.setblocking(False)
        self._req.bind((0, 0))
        self._evt: Optional[pysocket.socket] = None
        try:
            self._evt = pysocket.socket(
                pysocket.AF_NETLINK, pysocket.SOCK_RAW, NETLINK_ROUTE
            )
            self._evt.setblocking(False)
            self._evt.bind((0, _EVENT_GROUPS))
        except OSError:
            self._evt = None  # events unavailable (no CAP_NET_ADMIN etc.)
        self._pid = self._req.getsockname()[0]

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Attach both sockets to the running event loop."""
        if self._started:
            return
        loop = asyncio.get_running_loop()
        loop.add_reader(self._req.fileno(), self._on_req_readable)
        if self._evt is not None:
            loop.add_reader(self._evt.fileno(), self._on_evt_readable)
        self._started = True

    def close(self) -> None:
        if self._started:
            loop = asyncio.get_event_loop()
            loop.remove_reader(self._req.fileno())
            if self._evt is not None:
                loop.remove_reader(self._evt.fileno())
            self._started = False
        self._req.close()
        if self._evt is not None:
            self._evt.close()

    # -- request plane -----------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    async def _request(self, payload: bytes, seq: int, dump: bool) -> List[object]:
        """Send one message, await its ack (or NLMSG_DONE for dumps)."""
        if not self._started:
            self.start()
        async with self._req_lock:
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._pending[seq] = fut
            if dump:
                self._dump_acc[seq] = []
            try:
                self._req.send(payload)
                return await asyncio.wait_for(fut, timeout=10.0)
            finally:
                self._pending.pop(seq, None)
                self._dump_acc.pop(seq, None)

    def _on_req_readable(self) -> None:
        try:
            data = self._req.recv(1 << 18)
        except (BlockingIOError, InterruptedError):
            return
        for msg in self.codec.decode(data):
            if isinstance(msg, NlAck):
                fut = self._pending.get(msg.seq)
                if fut and not fut.done():
                    if msg.error == 0:
                        fut.set_result([])
                    else:
                        fut.set_exception(
                            NetlinkSocketError(
                                -msg.error, os.strerror(-msg.error)
                            )
                        )
            elif isinstance(msg, NlDone):
                fut = self._pending.get(msg.seq)
                if fut and not fut.done():
                    fut.set_result(self._dump_acc.get(msg.seq, []))
            else:
                # requests are serialized under _req_lock, so at most one
                # dump accumulator is open — parts belong to it
                for acc in self._dump_acc.values():
                    acc.append(msg)
                    break

    # -- event plane -------------------------------------------------------

    def _on_evt_readable(self) -> None:
        try:
            data = self._evt.recv(1 << 18)
        except (BlockingIOError, InterruptedError):
            return
        for msg in self.codec.decode(data):
            self._handle_event(msg)

    def _handle_event(self, msg: object) -> None:
        if isinstance(msg, NlLink):
            info = self._ifaces.get(msg.if_index)
            if msg.is_del:
                self._ifaces.pop(msg.if_index, None)
                if info is not None:
                    info.is_up = False
                    self._publish_iface(info)
                return
            if info is None:
                info = InterfaceInfo(
                    if_name=msg.if_name, is_up=msg.is_up, if_index=msg.if_index
                )
                self._ifaces[msg.if_index] = info
            else:
                info.is_up = msg.is_up
                if msg.if_name:
                    info.if_name = msg.if_name
            self._publish_iface(info)
        elif isinstance(msg, NlNeighbor):
            if self.neighbor_events_queue is not None:
                self.neighbor_events_queue.push(msg)
        elif isinstance(msg, NlAddr):
            info = self._ifaces.get(msg.if_index)
            if info is None:
                return
            if msg.is_del:
                if msg.prefix in info.networks:
                    info.networks.remove(msg.prefix)
            elif msg.prefix not in info.networks:
                info.networks.append(msg.prefix)
            self._publish_iface(info)

    def _publish_iface(self, info: InterfaceInfo) -> None:
        if self.events_queue is not None:
            self.events_queue.push(
                InterfaceInfo(
                    if_name=info.if_name,
                    is_up=info.is_up,
                    if_index=info.if_index,
                    networks=list(info.networks),
                )
            )

    # -- route/addr operations ----------------------------------------------

    async def add_route(self, route: NlRoute) -> None:
        seq = self._next_seq()
        if route.family == AF_INET6 and len(route.nexthops) > 1:
            # IPv6 multipath: kernel ignores NLM_F_REPLACE -> delete first
            try:
                await self.delete_route(route)
            except NetlinkSocketError as e:
                if e.errno not in (errno.ENOENT, errno.ESRCH):
                    raise
            seq = self._next_seq()
            payload = self.codec.encode_route(
                route, is_del=False, replace=False, seq=seq, pid=self._pid
            )
        else:
            payload = self.codec.encode_route(
                route, is_del=False, replace=True, seq=seq, pid=self._pid
            )
        await self._request(payload, seq, dump=False)

    async def delete_route(self, route: NlRoute) -> None:
        seq = self._next_seq()
        payload = self.codec.encode_route(
            route, is_del=True, seq=seq, pid=self._pid
        )
        await self._request(payload, seq, dump=False)

    async def add_if_address(self, if_index: int, prefix: str) -> None:
        seq = self._next_seq()
        payload = self.codec.encode_addr(if_index, prefix, seq=seq, pid=self._pid)
        await self._request(payload, seq, dump=False)

    async def del_if_address(self, if_index: int, prefix: str) -> None:
        seq = self._next_seq()
        payload = self.codec.encode_addr(
            if_index, prefix, is_del=True, seq=seq, pid=self._pid
        )
        await self._request(payload, seq, dump=False)

    # -- dumps ---------------------------------------------------------------

    async def _dump(self, rtm_type: int, family: int = 0) -> List[object]:
        seq = self._next_seq()
        payload = self.codec.encode_dump(rtm_type, family, seq=seq, pid=self._pid)
        return await self._request(payload, seq, dump=True)

    async def get_all_links(self) -> List[NlLink]:
        return [m for m in await self._dump(RTM_GETLINK) if isinstance(m, NlLink)]

    async def get_all_addrs(self) -> List[NlAddr]:
        return [m for m in await self._dump(RTM_GETADDR) if isinstance(m, NlAddr)]

    async def get_all_routes(
        self, protocol: Optional[int] = None
    ) -> List[NlRoute]:
        out: List[NlRoute] = []
        for fam in (AF_INET, AF_INET6, AF_MPLS):
            for m in await self._dump(RTM_GETROUTE, family=fam):
                if isinstance(m, tuple):
                    route, is_del = m
                    if not is_del and (
                        protocol is None or route.protocol == protocol
                    ):
                        out.append(route)
        return out
