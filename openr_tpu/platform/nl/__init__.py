"""openr_tpu.platform.nl — netlink platform layer.

Reference parity: openr/nl/ (NetlinkProtocolSocket + message codecs,
~5.7k LoC C++).  Here the codec is native C++ (native/nl_codec.cc, loaded
via ctypes) and the async socket driver is Python asyncio.
"""

from openr_tpu.platform.nl.codec import (  # noqa: F401
    AF_INET,
    AF_INET6,
    AF_MPLS,
    LabelAction,
    NlCodec,
    NlNexthop,
    NlRoute,
)
from openr_tpu.platform.nl.nl_socket import (  # noqa: F401
    NetlinkProtocolSocket,
    NetlinkSocketError,
)
from openr_tpu.platform.nl.mock import (  # noqa: F401
    MockNetlinkProtocolSocket,
    NetlinkEventsInjector,
)
