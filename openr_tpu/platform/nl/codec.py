"""ctypes binding for the native rtnetlink codec (native/nl_codec.cc).

Mirrors the C structs exactly (pack=1) and converts to/from the Python
dataclasses `NlRoute`/`NlNexthop` used by the rest of the platform layer.
Reference parity: openr/nl/NetlinkRouteMessage.h:58 and siblings.
"""

from __future__ import annotations

import ctypes
import enum
import ipaddress
import socket as pysocket
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from openr_tpu.common.native import load_native_lib

AF_INET = int(pysocket.AF_INET)
AF_INET6 = int(pysocket.AF_INET6)
AF_MPLS = 28  # linux/socket.h

# rtnetlink message types (linux/rtnetlink.h)
RTM_NEWROUTE = 24
RTM_DELROUTE = 25
RTM_GETROUTE = 26
RTM_NEWLINK = 16
RTM_DELLINK = 17
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_DELADDR = 21
RTM_GETADDR = 22
RTM_GETNEIGH = 30

_MAX_NEXTHOPS = 128
_MAX_LABELS = 16
_IFNAME = 32


class LabelAction(enum.IntEnum):
    """MPLS nexthop label operation (Network.thrift MplsActionCode)."""

    NONE = 0
    PUSH = 1
    SWAP = 2
    PHP = 3
    POP_AND_LOOKUP = 4


class Kind(enum.IntEnum):
    LINK = 1
    ADDR = 2
    ROUTE = 3
    NEIGH = 4
    ACK = 5
    DONE = 6


class _CNexthop(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("family", ctypes.c_uint8),
        ("gateway", ctypes.c_uint8 * 16),
        ("if_index", ctypes.c_int32),
        ("weight", ctypes.c_uint32),
        ("label_action", ctypes.c_uint8),
        ("label_count", ctypes.c_uint8),
        ("labels", ctypes.c_uint32 * _MAX_LABELS),
    ]


class _CRoute(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("family", ctypes.c_uint8),
        ("prefix_len", ctypes.c_uint8),
        ("dst", ctypes.c_uint8 * 16),
        ("mpls_label", ctypes.c_uint32),
        ("table", ctypes.c_uint8),
        ("protocol", ctypes.c_uint8),
        ("route_type", ctypes.c_uint8),
        ("priority", ctypes.c_uint32),
        ("nh_count", ctypes.c_uint32),
        ("nh", _CNexthop * _MAX_NEXTHOPS),
    ]


class _CMsg(ctypes.Structure):
    _pack_ = 1
    _fields_ = [
        ("kind", ctypes.c_uint16),
        ("nlmsg_type", ctypes.c_uint16),
        ("seq", ctypes.c_uint32),
        ("error", ctypes.c_int32),
        ("is_del", ctypes.c_uint8),
        ("if_index", ctypes.c_int32),
        ("if_flags", ctypes.c_uint32),
        ("is_up", ctypes.c_uint8),
        ("if_name", ctypes.c_char * _IFNAME),
        ("family", ctypes.c_uint8),
        ("prefix_len", ctypes.c_uint8),
        ("addr_valid", ctypes.c_uint8),
        ("addr", ctypes.c_uint8 * 16),
        ("neigh_state", ctypes.c_uint16),
        ("route", _CRoute),
    ]


@dataclass
class NlNexthop:
    """One route nexthop: gateway + oif + optional MPLS label op."""

    gateway: Optional[str] = None  # IP address string
    if_index: int = -1
    weight: int = 0
    label_action: LabelAction = LabelAction.NONE
    labels: Tuple[int, ...] = ()


@dataclass
class NlRoute:
    """A kernel route: IPv4/IPv6 `prefix` or an MPLS incoming `label`."""

    prefix: Optional[str] = None  # "net/len"; None for MPLS routes
    label: Optional[int] = None  # AF_MPLS incoming label
    nexthops: List[NlNexthop] = field(default_factory=list)
    protocol: int = 99  # openr's kernel route protocol id
    table: int = 0  # 0 -> RT_TABLE_MAIN
    priority: int = 0

    @property
    def family(self) -> int:
        if self.label is not None:
            return AF_MPLS
        net = ipaddress.ip_network(self.prefix, strict=False)
        return AF_INET if net.version == 4 else AF_INET6

    def key(self) -> Tuple:
        return (self.prefix, self.label, self.table)


@dataclass
class NlLink:
    if_index: int
    if_name: str
    is_up: bool
    flags: int = 0
    is_del: bool = False


@dataclass
class NlAddr:
    if_index: int
    prefix: str  # "addr/len"
    is_del: bool = False


@dataclass
class NlNeighbor:
    if_index: int
    address: str
    state: int
    is_del: bool = False


@dataclass
class NlAck:
    seq: int
    error: int  # 0 = success, else -errno


@dataclass
class NlDone:
    seq: int


def _pack_ip(addr: str, family: int) -> bytes:
    return pysocket.inet_pton(
        pysocket.AF_INET if family == AF_INET else pysocket.AF_INET6, addr
    )


def _unpack_ip(raw: bytes, family: int) -> str:
    if family == AF_INET:
        return pysocket.inet_ntop(pysocket.AF_INET, bytes(raw[:4]))
    return pysocket.inet_ntop(pysocket.AF_INET6, bytes(raw[:16]))


class NlCodec:
    """Thin stateless wrapper over libnl_codec.so."""

    def __init__(self) -> None:
        lib = load_native_lib("nl_codec")
        assert lib.onl_msg_size() == ctypes.sizeof(_CMsg), "ABI drift: OnlMsg"
        assert lib.onl_route_size() == ctypes.sizeof(_CRoute), "ABI drift: OnlRoute"
        lib.onl_encode_route.restype = ctypes.c_int
        lib.onl_encode_addr.restype = ctypes.c_int
        lib.onl_encode_dump.restype = ctypes.c_int
        lib.onl_decode.restype = ctypes.c_int
        self._lib = lib
        self._buf = ctypes.create_string_buffer(64 * 1024)
        self._msgs = (_CMsg * 512)()

    # -- encode ------------------------------------------------------------

    def _to_c_route(self, route: NlRoute) -> _CRoute:
        c = _CRoute()
        fam = route.family
        c.family = fam
        c.protocol = route.protocol
        c.table = route.table
        c.priority = route.priority
        if fam == AF_MPLS:
            c.mpls_label = route.label
        else:
            net = ipaddress.ip_network(route.prefix, strict=False)
            c.prefix_len = net.prefixlen
            raw = net.network_address.packed
            ctypes.memmove(c.dst, raw, len(raw))
        if len(route.nexthops) > _MAX_NEXTHOPS:
            raise ValueError(f"too many nexthops ({len(route.nexthops)})")
        c.nh_count = len(route.nexthops)
        for i, nh in enumerate(route.nexthops):
            cn = c.nh[i]
            cn.if_index = nh.if_index
            cn.weight = nh.weight
            cn.label_action = int(nh.label_action)
            if len(nh.labels) > _MAX_LABELS:
                raise ValueError(f"label stack too deep ({len(nh.labels)})")
            cn.label_count = len(nh.labels)
            for j, lbl in enumerate(nh.labels):
                cn.labels[j] = lbl
            if nh.gateway:
                gw = ipaddress.ip_address(nh.gateway)
                cn.family = AF_INET if gw.version == 4 else AF_INET6
                ctypes.memmove(cn.gateway, gw.packed, len(gw.packed))
        return c

    def encode_route(
        self,
        route: NlRoute,
        is_del: bool = False,
        replace: bool = True,
        seq: int = 0,
        pid: int = 0,
    ) -> bytes:
        c = self._to_c_route(route)
        n = self._lib.onl_encode_route(
            ctypes.byref(c), int(is_del), int(replace), seq, pid,
            self._buf, len(self._buf),
        )
        if n < 0:
            raise ValueError(f"route encode failed: {route}")
        return self._buf.raw[:n]

    def encode_addr(
        self,
        if_index: int,
        prefix: str,
        is_del: bool = False,
        seq: int = 0,
        pid: int = 0,
    ) -> bytes:
        iface = ipaddress.ip_interface(prefix)
        fam = AF_INET if iface.version == 4 else AF_INET6
        raw = iface.ip.packed
        n = self._lib.onl_encode_addr(
            int(is_del), seq, pid, if_index, fam,
            (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw),
            iface.network.prefixlen, self._buf, len(self._buf),
        )
        if n < 0:
            raise ValueError(f"addr encode failed: {prefix}")
        return self._buf.raw[:n]

    def encode_dump(self, rtm_type: int, family: int = 0, seq: int = 0,
                    pid: int = 0) -> bytes:
        n = self._lib.onl_encode_dump(rtm_type, family, seq, pid, self._buf,
                                      len(self._buf))
        if n < 0:
            raise ValueError("dump encode failed")
        return self._buf.raw[:n]

    # -- decode ------------------------------------------------------------

    def _from_c_route(self, c: _CRoute, is_del: bool) -> NlRoute:
        route = NlRoute(protocol=c.protocol, table=c.table, priority=c.priority)
        if c.family == AF_MPLS:
            route.label = c.mpls_label
        else:
            net_addr = _unpack_ip(bytes(c.dst), c.family)
            route.prefix = f"{net_addr}/{c.prefix_len}"
        for i in range(c.nh_count):
            cn = c.nh[i]
            nh = NlNexthop(
                if_index=cn.if_index,
                weight=cn.weight,
                label_action=LabelAction(cn.label_action),
                labels=tuple(cn.labels[j] for j in range(cn.label_count)),
            )
            if cn.family in (AF_INET, AF_INET6):
                nh.gateway = _unpack_ip(bytes(cn.gateway), cn.family)
            route.nexthops.append(nh)
        return route

    def decode(self, data: bytes) -> List[object]:
        """Decode a recv buffer into NlLink/NlAddr/NlRoute/NlNeighbor/
        NlAck/NlDone events (is_del routes come back as (route, True)).
        Buffers holding more messages than the staging array are decoded
        in chunks via the codec's `consumed` cursor — nothing is dropped."""
        out: List[object] = []
        offset = 0
        while offset < len(data):
            consumed = ctypes.c_int(0)
            n = self._lib.onl_decode(
                data[offset:], len(data) - offset, self._msgs, len(self._msgs),
                ctypes.byref(consumed),
            )
            self._collect(n, out)
            if consumed.value <= 0:
                break
            offset += consumed.value
        return out

    def _collect(self, n: int, out: List[object]) -> None:
        for i in range(n):
            m = self._msgs[i]
            kind = m.kind
            if kind == Kind.ACK:
                out.append(NlAck(seq=m.seq, error=m.error))
            elif kind == Kind.DONE:
                out.append(NlDone(seq=m.seq))
            elif kind == Kind.LINK:
                out.append(
                    NlLink(
                        if_index=m.if_index,
                        if_name=m.if_name.decode(),
                        is_up=bool(m.is_up),
                        flags=m.if_flags,
                        is_del=bool(m.is_del),
                    )
                )
            elif kind == Kind.ADDR and m.addr_valid:
                addr = _unpack_ip(bytes(m.addr), m.family)
                out.append(
                    NlAddr(
                        if_index=m.if_index,
                        prefix=f"{addr}/{m.prefix_len}",
                        is_del=bool(m.is_del),
                    )
                )
            elif kind == Kind.ROUTE:
                route = self._from_c_route(m.route, bool(m.is_del))
                out.append((route, bool(m.is_del)))
            elif kind == Kind.NEIGH and m.addr_valid:
                out.append(
                    NlNeighbor(
                        if_index=m.if_index,
                        address=_unpack_ip(bytes(m.addr), m.family),
                        state=m.neigh_state,
                        is_del=bool(m.is_del),
                    )
                )


_codec: Optional[NlCodec] = None


def get_codec() -> NlCodec:
    global _codec
    if _codec is None:
        _codec = NlCodec()
    return _codec
