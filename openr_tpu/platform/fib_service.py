"""FibService — the platform agent that programs routes into the kernel.

Reference parity: openr/platform/NetlinkFibHandler.{h,cpp} (thrift
`FibService`, if/Platform.thrift:78-160) served over fbthrift on
`fib_port`; runs in-process (Main.cpp:252-278) or as the standalone
`platform_linux` binary (LinuxPlatformMain.cpp:26-69).

Pieces:
  * NetlinkFibHandler  — per-client route tables programmed through a
    BaseNetlinkProtocolSocket (real kernel or mock)
  * FibServiceServer   — serves the handler over TCP with the repo's
    framed-JSON RPC (the fbthrift-on-fib_port equivalent)
  * RemoteFibAgent     — client-side FibAgent adapter for Fib → TCP agent
  * NetlinkFibAgent    — in-process FibAgent adapter (no TCP hop)

Route conversion maps the framework wire types (UnicastRoute/MplsRoute,
Network.thrift shapes) onto NlRoute/NlNexthop, resolving interface names
to kernel ifindexes via the link dump (NetlinkFibHandler.h keeps the same
ifName<->ifIndex caches).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import json
import time
from typing import Dict, List, Optional

from openr_tpu.common.runtime import CounterMap
from openr_tpu.ctrl.server import read_frame, write_frame
from openr_tpu.fib.fib import FibAgent, FibAgentError
from openr_tpu.platform.nl.codec import LabelAction, NlNexthop, NlRoute
from openr_tpu.platform.nl.nl_socket import BaseNetlinkProtocolSocket
from openr_tpu.types import (
    MplsAction,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
    normalize_prefix,
)

#: kernel route-protocol id for routes we own (reference uses 99/openr)
ROUTE_PROTO_OPENR = 99

#: SwitchRunState (Platform.thrift:42-48; the reference enum itself
#: skips 3 — the gap between CONFIGURED and EXITING is deliberate)
SWITCH_RUN_STATE_UNINITIALIZED = 0
SWITCH_RUN_STATE_INITIALIZED = 1
SWITCH_RUN_STATE_CONFIGURED = 2
SWITCH_RUN_STATE_EXITING = 4
#: FibService client ids (if/Platform.thrift ClientId); openr is 786
CLIENT_ID_OPENR = 786


def _nh_to_nl(nh: NextHop, if_index: int, mpls_route: bool) -> NlNexthop:
    action = LabelAction.NONE
    labels: tuple = ()
    if nh.mpls_action is not None:
        code = nh.mpls_action.action
        if code == MplsActionCode.PUSH:
            action = LabelAction.PUSH
            labels = tuple(nh.mpls_action.push_labels or ())
        elif code == MplsActionCode.SWAP:
            action = LabelAction.SWAP
            labels = (nh.mpls_action.swap_label,) if nh.mpls_action.swap_label else ()
        elif code == MplsActionCode.PHP:
            action = LabelAction.PHP
        elif code == MplsActionCode.POP_AND_LOOKUP:
            action = LabelAction.POP_AND_LOOKUP
    return NlNexthop(
        gateway=nh.address or None,
        if_index=if_index,
        weight=nh.weight,
        label_action=action,
        labels=labels,
    )


def _nl_to_nh(nh: NlNexthop, if_name: str) -> NextHop:
    mpls: Optional[MplsAction] = None
    if nh.label_action == LabelAction.PUSH:
        mpls = MplsAction(action=MplsActionCode.PUSH, push_labels=tuple(nh.labels))
    elif nh.label_action == LabelAction.SWAP:
        mpls = MplsAction(
            action=MplsActionCode.SWAP,
            swap_label=nh.labels[0] if nh.labels else None,
        )
    elif nh.label_action == LabelAction.PHP:
        mpls = MplsAction(action=MplsActionCode.PHP)
    elif nh.label_action == LabelAction.POP_AND_LOOKUP:
        mpls = MplsAction(action=MplsActionCode.POP_AND_LOOKUP)
    return NextHop(
        address=nh.gateway or "", if_name=if_name, weight=nh.weight,
        mpls_action=mpls,
    )


class NetlinkFibHandler:
    """FibService implementation over a netlink socket.

    Keeps an authoritative per-client view of programmed routes (the
    reference reads it back from the kernel via getRouteTableByClient; we
    keep both: in-memory table + kernel dump filtered by protocol)."""

    def __init__(self, nl_sock: BaseNetlinkProtocolSocket) -> None:
        self.nl = nl_sock
        self.counters = CounterMap()
        self._alive_since = time.time()  # orlint: disable=clock-now (epoch aliveSince for the thrift API, not protocol time)
        self._unicast: Dict[int, Dict[str, UnicastRoute]] = {}
        self._mpls: Dict[int, Dict[int, MplsRoute]] = {}
        self._if_name_to_index: Dict[str, int] = {}
        self._if_index_to_name: Dict[int, str] = {}
        self._neighbor_listeners: List = []

    async def _refresh_links(self) -> None:
        # rebuild from scratch: a flapped interface can come back with a
        # new ifindex, and a stale mapping would program the wrong device
        name_to_index: Dict[str, int] = {}
        index_to_name: Dict[int, str] = {}
        for link in await self.nl.get_all_links():
            if not link.is_del:
                name_to_index[link.if_name] = link.if_index
                index_to_name[link.if_index] = link.if_name
        self._if_name_to_index = name_to_index
        self._if_index_to_name = index_to_name

    async def _resolve_if(self, if_name: str) -> int:
        if not if_name:
            return -1
        if if_name not in self._if_name_to_index:
            await self._refresh_links()
        idx = self._if_name_to_index.get(if_name)
        if idx is None:
            raise FibAgentError(f"unknown interface {if_name!r}")
        return idx

    async def _to_nl_unicast(self, route: UnicastRoute) -> NlRoute:
        nhs = [
            _nh_to_nl(nh, await self._resolve_if(nh.if_name), mpls_route=False)
            for nh in route.next_hops
        ]
        return NlRoute(
            prefix=normalize_prefix(route.dest),
            nexthops=nhs,
            protocol=ROUTE_PROTO_OPENR,
        )

    async def _to_nl_mpls(self, route: MplsRoute) -> NlRoute:
        nhs = [
            _nh_to_nl(nh, await self._resolve_if(nh.if_name), mpls_route=True)
            for nh in route.next_hops
        ]
        return NlRoute(
            label=route.top_label, nexthops=nhs, protocol=ROUTE_PROTO_OPENR
        )

    # -- FibService surface (if/Platform.thrift:78-160) ---------------------

    async def _add_with_stale_if_retry(self, build) -> None:
        """Program one route; on ENODEV re-resolve interfaces once (the
        cached ifindex may belong to a recreated device) and retry."""
        import errno as _errno

        try:
            await self.nl.add_route(await build())
        except OSError as e:
            if getattr(e, "errno", None) != _errno.ENODEV:
                raise
            await self._refresh_links()
            await self.nl.add_route(await build())

    async def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        table = self._unicast.setdefault(client_id, {})
        for route in routes:
            await self._add_with_stale_if_retry(
                lambda route=route: self._to_nl_unicast(route)
            )
            table[normalize_prefix(route.dest)] = route
            self.counters.bump("fib.nl.unicast_adds")

    async def delete_unicast_routes(
        self, client_id: int, prefixes: List[str]
    ) -> None:
        table = self._unicast.setdefault(client_id, {})
        for prefix in prefixes:
            prefix = normalize_prefix(prefix)
            route = table.pop(prefix, None)
            nl_route = NlRoute(prefix=prefix, protocol=ROUTE_PROTO_OPENR)
            try:
                await self.nl.delete_route(nl_route)
            except OSError:
                if route is not None:  # existed in our table: real failure
                    raise
            self.counters.bump("fib.nl.unicast_dels")

    async def add_mpls_routes(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        table = self._mpls.setdefault(client_id, {})
        for route in routes:
            await self._add_with_stale_if_retry(
                lambda route=route: self._to_nl_mpls(route)
            )
            table[route.top_label] = route
            self.counters.bump("fib.nl.mpls_adds")

    async def delete_mpls_routes(self, client_id: int, labels: List[int]) -> None:
        table = self._mpls.setdefault(client_id, {})
        for label in labels:
            route = table.pop(label, None)
            try:
                await self.nl.delete_route(
                    NlRoute(label=label, protocol=ROUTE_PROTO_OPENR)
                )
            except OSError:
                if route is not None:
                    raise
            self.counters.bump("fib.nl.mpls_dels")

    async def sync_fib(self, client_id: int, routes: List[UnicastRoute]) -> None:
        """Replace the client's whole unicast table (syncFib semantics:
        delete stale, add/update the rest)."""
        table = self._unicast.setdefault(client_id, {})
        wanted = {normalize_prefix(r.dest) for r in routes}
        stale = [p for p in table if p not in wanted]
        await self.delete_unicast_routes(client_id, stale)
        await self.add_unicast_routes(client_id, routes)
        self.counters.bump("fib.nl.sync_fib")

    async def sync_mpls_fib(self, client_id: int, routes: List[MplsRoute]) -> None:
        table = self._mpls.setdefault(client_id, {})
        wanted = {r.top_label for r in routes}
        stale = [l for l in table if l not in wanted]
        await self.delete_mpls_routes(client_id, stale)
        await self.add_mpls_routes(client_id, routes)
        self.counters.bump("fib.nl.sync_mpls_fib")

    async def get_route_table_by_client(
        self, client_id: int
    ) -> List[UnicastRoute]:
        return list(self._unicast.get(client_id, {}).values())

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> List[MplsRoute]:
        return list(self._mpls.get(client_id, {}).values())

    async def add_unicast_route(
        self, client_id: int, route: UnicastRoute
    ) -> None:
        """Singular convenience form (Platform.thrift:88)."""
        await self.add_unicast_routes(client_id, [route])

    async def delete_unicast_route(self, client_id: int, prefix: str) -> None:
        """Singular convenience form (Platform.thrift:93)."""
        await self.delete_unicast_routes(client_id, [prefix])

    async def get_switch_run_state(self) -> int:
        """SwitchRunState (Platform.thrift:42-48,78): a live netlink
        handler is always fully CONFIGURED, like the reference's
        NetlinkFibHandler::getSwitchRunState."""
        return SWITCH_RUN_STATE_CONFIGURED

    def register_neighbor_listener(self, cb) -> None:
        """cb(neighbor_ips: List[str], is_up: bool) — the
        NeighborListenerClientForFibagent.neighborsChanged equivalent
        (Platform.thrift:146; reference invokeNeighborListeners)."""
        self._neighbor_listeners.append(cb)

    async def send_neighbor_down_info(self, neighbor_ips: List[str]) -> None:
        """Fan a neighbor-down event out to registered listeners
        (Platform.thrift:146, NetlinkFibHandler.cpp:697-708).  Listener
        failures are isolated: one throwing callback must not starve the
        others or error the peer that merely reported the event."""
        self.counters.bump("fib.neighbor_down_notifications")
        for cb in list(self._neighbor_listeners):
            try:
                cb(list(neighbor_ips), False)
            except Exception:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "neighbor-down listener failed"
                )
                self.counters.bump("fib.neighbor_listener_errors")

    async def get_kernel_routes(self) -> List[NlRoute]:
        """Dump our protocol's routes straight from the kernel."""
        return await self.nl.get_all_routes(protocol=ROUTE_PROTO_OPENR)

    async def alive_since(self) -> float:
        return self._alive_since

    async def get_counters(self) -> Dict[str, float]:
        return self.counters.dump()


class NetlinkFibAgent(FibAgent):
    """In-process FibAgent over a NetlinkFibHandler (Main.cpp:252-278
    in-process mode)."""

    def __init__(
        self, handler: NetlinkFibHandler, client_id: int = CLIENT_ID_OPENR
    ) -> None:
        self.handler = handler
        self.client_id = client_id

    async def add_unicast_routes(self, routes: List[UnicastRoute]) -> None:
        await self.handler.add_unicast_routes(self.client_id, routes)

    async def delete_unicast_routes(self, prefixes: List[str]) -> None:
        await self.handler.delete_unicast_routes(self.client_id, prefixes)

    async def add_mpls_routes(self, routes: List[MplsRoute]) -> None:
        await self.handler.add_mpls_routes(self.client_id, routes)

    async def delete_mpls_routes(self, labels: List[int]) -> None:
        await self.handler.delete_mpls_routes(self.client_id, labels)

    async def sync_fib(self, routes, mpls_routes) -> None:
        await self.handler.sync_fib(self.client_id, routes)
        await self.handler.sync_mpls_fib(self.client_id, mpls_routes)

    async def alive_since(self) -> float:
        return await self.handler.alive_since()


class FibServiceServer:
    """TCP front-end for a NetlinkFibHandler: framed-JSON unary RPC on
    fib_port (the fbthrift FibService server equivalent)."""

    def __init__(
        self,
        handler: NetlinkFibHandler,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_tasks: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        for t in list(self._conn_tasks):
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _on_connection(self, reader, writer) -> None:
        self._conn_tasks.add(asyncio.current_task())
        try:
            while True:
                msg = await read_frame(reader)
                if msg is None:
                    break
                rid = msg.get("id")
                try:
                    result = await self._dispatch(
                        msg.get("method", ""), msg.get("params") or {}
                    )
                    write_frame(writer, {"id": rid, "result": result})
                except Exception as e:  # noqa: BLE001
                    write_frame(writer, {"id": rid, "error": str(e)})
                await writer.drain()
        finally:
            writer.close()
            self._conn_tasks.discard(asyncio.current_task())

    async def _dispatch(self, method: str, params: dict):
        client_id = params.get("client_id", CLIENT_ID_OPENR)
        if method == "add_unicast_routes":
            await self.handler.add_unicast_routes(
                client_id,
                [UnicastRoute.from_wire(r) for r in params["routes"]],
            )
        elif method == "delete_unicast_routes":
            await self.handler.delete_unicast_routes(
                client_id, params["prefixes"]
            )
        elif method == "add_mpls_routes":
            await self.handler.add_mpls_routes(
                client_id, [MplsRoute.from_wire(r) for r in params["routes"]]
            )
        elif method == "delete_mpls_routes":
            await self.handler.delete_mpls_routes(client_id, params["labels"])
        elif method == "sync_fib":
            await self.handler.sync_fib(
                client_id,
                [UnicastRoute.from_wire(r) for r in params["routes"]],
            )
        elif method == "sync_mpls_fib":
            await self.handler.sync_mpls_fib(
                client_id, [MplsRoute.from_wire(r) for r in params["routes"]]
            )
        elif method == "get_route_table_by_client":
            return [
                r.to_wire()
                for r in await self.handler.get_route_table_by_client(client_id)
            ]
        elif method == "get_mpls_route_table_by_client":
            return [
                r.to_wire()
                for r in await self.handler.get_mpls_route_table_by_client(
                    client_id
                )
            ]
        elif method == "add_unicast_route":
            await self.handler.add_unicast_route(
                client_id, UnicastRoute.from_wire(params["route"])
            )
        elif method == "delete_unicast_route":
            await self.handler.delete_unicast_route(
                client_id, params["prefix"]
            )
        elif method == "get_switch_run_state":
            return await self.handler.get_switch_run_state()
        elif method == "send_neighbor_down_info":
            await self.handler.send_neighbor_down_info(
                params["neighbor_ips"]
            )
        elif method == "alive_since":
            return await self.handler.alive_since()
        elif method == "get_counters":
            return await self.handler.get_counters()
        else:
            raise ValueError(f"unknown FibService method {method!r}")
        return None


class RemoteFibAgent(FibAgent):
    """Fib's client to a (possibly standalone) FibService on fib_port —
    the createFibClient path (fib/Fib.h:55).  Reconnects lazily; any
    transport error surfaces as FibAgentError so Fib's retry/backoff and
    keepalive logic drives recovery."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 60100,
        client_id: int = CLIENT_ID_OPENR,
    ) -> None:
        self.host = host
        self.port = port
        self.client_id = client_id
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()

    async def _ensure_connected(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        try:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )
        except OSError as e:
            raise FibAgentError(f"fib agent unreachable: {e}") from e

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    async def _call(self, method: str, **params):
        async with self._lock:
            await self._ensure_connected()
            params.setdefault("client_id", self.client_id)
            rid = next(self._ids)
            try:
                write_frame(self._writer, {
                    "id": rid, "method": method, "params": params,
                })
                await self._writer.drain()
                resp = await read_frame(self._reader)
            except (OSError, json.JSONDecodeError) as e:
                await self.close()
                raise FibAgentError(f"fib agent transport error: {e}") from e
            if resp is None:
                await self.close()
                raise FibAgentError("fib agent connection closed")
            if resp.get("error"):
                raise FibAgentError(resp["error"])
            return resp.get("result")

    async def add_unicast_routes(self, routes: List[UnicastRoute]) -> None:
        await self._call(
            "add_unicast_routes", routes=[r.to_wire() for r in routes]
        )

    async def delete_unicast_routes(self, prefixes: List[str]) -> None:
        await self._call("delete_unicast_routes", prefixes=prefixes)

    async def add_mpls_routes(self, routes: List[MplsRoute]) -> None:
        await self._call(
            "add_mpls_routes", routes=[r.to_wire() for r in routes]
        )

    async def delete_mpls_routes(self, labels: List[int]) -> None:
        await self._call("delete_mpls_routes", labels=labels)

    async def sync_fib(self, routes, mpls_routes) -> None:
        await self._call("sync_fib", routes=[r.to_wire() for r in routes])
        await self._call(
            "sync_mpls_fib", routes=[r.to_wire() for r in mpls_routes]
        )

    async def alive_since(self) -> float:
        return float(await self._call("alive_since"))

    async def get_route_table(self) -> List[UnicastRoute]:
        return [
            UnicastRoute.from_wire(r)
            for r in await self._call("get_route_table_by_client")
        ]

    async def get_counters(self) -> Dict[str, float]:
        return dict(await self._call("get_counters"))

    async def add_unicast_route(self, route: UnicastRoute) -> None:
        await self._call("add_unicast_route", route=route.to_wire())

    async def delete_unicast_route(self, prefix: str) -> None:
        await self._call("delete_unicast_route", prefix=prefix)

    async def get_switch_run_state(self) -> int:
        return int(await self._call("get_switch_run_state"))

    async def send_neighbor_down_info(self, neighbor_ips: List[str]) -> None:
        await self._call("send_neighbor_down_info", neighbor_ips=neighbor_ips)
