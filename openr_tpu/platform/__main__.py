"""Standalone platform daemon — `python -m openr_tpu.platform`.

Reference parity: the `platform_linux` binary
(openr/platform/LinuxPlatformMain.cpp:26-69): serve FibService over the
real kernel netlink socket on --fib-port, independent of the main daemon.
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from openr_tpu.platform.fib_service import FibServiceServer, NetlinkFibHandler
from openr_tpu.platform.nl import NetlinkProtocolSocket


async def run(host: str, port: int) -> None:
    nl = NetlinkProtocolSocket()
    nl.start()
    handler = NetlinkFibHandler(nl)
    server = FibServiceServer(handler, host=host, port=port)
    await server.start()
    logging.info("FibService listening on %s:%d", host, server.port)
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()
        nl.close()


def main() -> None:
    parser = argparse.ArgumentParser(description="openr_tpu platform daemon")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--fib-port", type=int, default=60100)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(run(args.host, args.fib_port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
