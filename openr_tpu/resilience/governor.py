"""BackendHealthGovernor — owns the device backend's health latch.

The pitch of this system is "replace the trusted scalar Dijkstra with a
batched device kernel" (PAPER §7).  That trade has three failure modes a
production deployment must survive without an operator:

1. **Hard outage** — dispatch raises (chaos ``tpu_fail``, a dead chip, a
   severed tunnel).  Before this module the latch was one-way: only
   chaos flipped ``TpuBackend.device_failed``; an organic dispatch
   exception fell back scalar for THAT build and re-paid the failing
   device on every subsequent rebuild.
2. **Silent data corruption (SDC)** — the kernel returns *wrong but
   plausible* tables (the classic large-fleet accelerator failure mode;
   chaos ``tpu_corrupt`` models it).  Nothing raised, so nothing in the
   old design could notice wrong routes being programmed into FIBs.
3. **Recovery** — once the device heals, something has to notice and
   re-trust it, and it must not re-trust a device that is still lying.

The governor solves all three with ONE mechanism: a
:class:`~openr_tpu.resilience.breaker.CircuitBreaker` around the device,
plus **shadow verification** — a configurable sample of device builds is
recomputed on the native/scalar SPF oracle and RIB-diffed (nexthop sets,
igp cost, plus non-finite/NaN guards on kernel-derived metrics).  A
mismatch or a run of dispatch failures opens the breaker: the backend is
quarantined, ``device_failed`` goes up, and — because
``Decision.device_available()`` reads that latch — route builds, the
serving plane, and what-if queries all degrade to the scalar engines
coherently.  While open, half-open probe builds (which MUST pass shadow
verification) are the only device traffic; a passing probe restores the
device.

The governor is the ONLY writer of ``device_failed`` outside chaos and
the backend itself — enforced statically by orlint's ``resilience-latch``
rule (analysis/passes/resilience_latch.py).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from openr_tpu.common.runtime import Clock, CounterMap, WallClock
from openr_tpu.resilience.breaker import (
    STATE_CLOSED,
    CircuitBreaker,
)

#: admit() verdicts
ADMIT_DEVICE = "device"
ADMIT_PROBE = "probe"
ADMIT_QUARANTINED = "quarantined"


class BackendHealthGovernor:
    """Health authority for one TpuBackend.

    The backend calls three hooks around every build:

    * :meth:`admit` — before touching the device.  ``"quarantined"``
      routes the build to the scalar oracle; ``"probe"`` marks this
      build as the half-open probe (it must shadow-verify to restore
      the device); ``"device"`` is the healthy fast path.
    * :meth:`record_dispatch_failure` — a device dispatch raised.
      Consecutive failures past the breaker threshold quarantine.
    * :meth:`after_device_build` — the device produced a RouteDb.
      Sampled builds (and every probe) are shadow-verified against the
      scalar oracle; on mismatch the device is quarantined and the
      *scalar* RouteDb replaces the corrupt device output, so the wrong
      answer never reaches the FIB once detected.
    """

    def __init__(
        self,
        backend,
        clock: Optional[Clock] = None,
        counters: Optional[CounterMap] = None,
        tracer=None,
        shadow_sample_every: int = 8,
        failure_threshold: int = 3,
        probe_backoff_initial_s: float = 1.0,
        probe_backoff_max_s: float = 30.0,
        jitter_pct: float = 0.1,
        seed: int = 0,
    ) -> None:
        from openr_tpu.tracing import disabled_tracer

        self.backend = backend
        self.clock = clock if clock is not None else WallClock()
        self.counters = counters if counters is not None else CounterMap()
        self.tracer = tracer if tracer is not None else disabled_tracer()
        self.shadow_sample_every = max(0, int(shadow_sample_every))
        self.breaker = CircuitBreaker(
            "backend",
            self.clock,
            failure_threshold=failure_threshold,
            backoff_initial_s=probe_backoff_initial_s,
            backoff_max_s=probe_backoff_max_s,
            jitter_pct=jitter_pct,
            seed=seed,
            counters=self.counters,
        )
        #: hard latch: chaos tpu_fail / operator force_quarantine.  While
        #: set, NO probes run (the fault owner declared the device dead);
        #: request_probe() clears it and makes the breaker probe-eligible
        self.injected = False
        self.quarantine_reason = ""
        #: device builds since the last shadow check; starts "due" so the
        #: FIRST device build of a boot is always verified — SDC present
        #: from cold start is caught before the first FIB sync settles
        self._builds_since_check = self.shadow_sample_every
        self._forced_probe = False
        self.num_shadow_checks = 0
        self.num_shadow_mismatches = 0
        self.num_quarantines = 0
        self.num_restores = 0
        self.num_dispatch_failures = 0
        self.last_probe: Dict[str, object] = {}
        self.last_mismatch: Dict[str, object] = {}
        self._sync_latch()

    # -- the latch (single writer) ------------------------------------------

    def _sync_latch(self) -> None:
        self.backend.device_failed = (
            self.injected or self.breaker.state != STATE_CLOSED
        )

    @property
    def quarantined(self) -> bool:
        return self.backend.device_failed

    # -- build hooks ---------------------------------------------------------

    def admit(self) -> str:
        """Gate one route build's device usage."""
        if self.injected:
            return ADMIT_QUARANTINED
        if self._forced_probe:
            # operator force_probe: run the device + full verification
            # regardless of breaker timing
            self._forced_probe = False
            return ADMIT_PROBE
        if self.breaker.state == STATE_CLOSED:
            return ADMIT_DEVICE
        if self.breaker.allow_request():
            return ADMIT_PROBE
        return ADMIT_QUARANTINED

    def abort_probe(self) -> None:
        """The admitted probe never reached the device (the build bailed
        to scalar for an eligibility reason, not a health reason):
        release the probe slot without scoring it."""
        self.breaker.release_probe()

    def record_dispatch_failure(self, exc: Optional[BaseException] = None) -> None:
        """A device dispatch raised (organic failure).  Counts toward the
        breaker threshold; past it the device is quarantined instead of
        being re-tried on every rebuild."""
        self.num_dispatch_failures += 1
        self.counters.bump("resilience.backend.dispatch_failures")
        was_quarantined = self.quarantined
        self.breaker.record_failure()
        self._sync_latch()
        if self.quarantined and not was_quarantined:
            self._note_quarantine(
                f"dispatch:{type(exc).__name__}" if exc is not None else "dispatch"
            )

    def after_device_build(
        self, db, area_link_states, prefix_state, probe: bool = False
    ) -> Tuple[object, bool]:
        """Returns ``(route_db, from_device)``.  ``from_device`` is False
        exactly when shadow verification replaced a corrupt device
        result with the scalar oracle's — the caller must then drop its
        incremental bases."""
        self._builds_since_check += 1
        due = (
            self.shadow_sample_every > 0
            and self._builds_since_check >= self.shadow_sample_every
        )
        if not probe and not due:
            return db, True
        self._builds_since_check = 0
        span = self.tracer.start_span(
            "resilience.probe" if probe else "resilience.shadow_check",
            module="resilience",
            probe=probe,
        )
        ok, scalar_db, reason = self._shadow_verify(
            db, area_link_states, prefix_state
        )
        self.tracer.end_span(span, passed=ok, reason=reason)
        if probe:
            self.last_probe = {
                "passed": ok,
                "reason": reason,
            }
        if ok:
            self.num_shadow_checks += 1
            self.counters.bump("resilience.backend.shadow_checks")
            if probe or self.breaker.state != STATE_CLOSED:
                was_quarantined = self.quarantined
                self.breaker.record_success()
                self.injected = False
                self._sync_latch()
                if was_quarantined and not self.quarantined:
                    self.num_restores += 1
                    self.counters.bump("resilience.backend.restores")
            return db, True
        # wrong-but-plausible device output: quarantine AND serve the
        # verified scalar answer for this build
        self.num_shadow_checks += 1
        self.counters.bump("resilience.backend.shadow_checks")
        self.num_shadow_mismatches += 1
        self.counters.bump("resilience.backend.shadow_mismatches")
        self.last_mismatch = {"reason": reason}
        was_quarantined = self.quarantined
        if probe and self.breaker.state != STATE_CLOSED:
            self.breaker.record_failure()  # failed probe: backoff doubles
        else:
            # sampled mismatch, or a FORCED probe that failed while the
            # breaker was closed: proven corruption quarantines outright
            self.breaker.force_open()
        self._sync_latch()
        if not was_quarantined:
            self._note_quarantine(f"shadow:{reason}")
        return scalar_db, False

    def _note_quarantine(self, reason: str) -> None:
        self.quarantine_reason = reason
        self.num_quarantines += 1
        self.counters.bump("resilience.backend.quarantines")

    # -- shadow verification -------------------------------------------------

    def _shadow_verify(
        self, device_db, area_link_states, prefix_state
    ) -> Tuple[bool, object, str]:
        """Device RouteDb vs the scalar oracle: (ok, scalar_db, reason).

        Checks, cheapest first: non-finite guard on kernel-derived
        metrics (NaN/inf igp_cost is *never* legitimate on a reachable
        route), then the full RIB diff — same prefix set, and per prefix
        the same nexthop set (address/iface/metric/area) and igp cost.
        The scalar db is computed ONCE and returned so a mismatching
        build can be served from it without a second solve."""
        for prefix, entry in device_db.unicast_routes.items():
            if not math.isfinite(entry.igp_cost):
                return False, self._scalar_db(area_link_states, prefix_state), (
                    f"non_finite:{prefix}"
                )
        scalar_db = self._scalar_db(area_link_states, prefix_state)
        dev = device_db.unicast_routes
        ref = scalar_db.unicast_routes
        if set(dev) != set(ref):
            missing = sorted(set(ref) - set(dev))[:3]
            extra = sorted(set(dev) - set(ref))[:3]
            return False, scalar_db, f"prefix_set:missing={missing}:extra={extra}"
        for prefix, d in dev.items():
            r = ref[prefix]
            if set(d.nexthops) != set(r.nexthops):
                return False, scalar_db, f"nexthops:{prefix}"
            if float(d.igp_cost) != float(r.igp_cost):
                return False, scalar_db, f"igp_cost:{prefix}"
            if d.do_not_install != r.do_not_install:
                return False, scalar_db, f"do_not_install:{prefix}"
        return True, scalar_db, ""

    def _scalar_db(self, area_link_states, prefix_state):
        return self.backend.solver.build_route_db(
            area_link_states, prefix_state
        )

    # -- operator / chaos controls -------------------------------------------

    def force_quarantine(self, reason: str = "operator") -> None:
        """Hard-quarantine the device (chaos tpu_fail inject, operator
        drain).  No probes run until request_probe/force_restore."""
        was = self.quarantined
        self.injected = True
        self.breaker.force_open()
        self._sync_latch()
        if not was:
            self._note_quarantine(reason)
        else:
            self.quarantine_reason = reason

    def request_probe(self, reason: str = "heal") -> None:
        """The fault owner healed the device: clear the hard latch and
        make the breaker probe-eligible NOW.  The device stays
        quarantined until a probe build passes shadow verification —
        heals are *probed*, never trusted blindly."""
        self.injected = False
        self.breaker.expire_hold()
        self.counters.bump("resilience.backend.probe_requests")
        self._sync_latch()

    def force_restore(self, reason: str = "operator") -> None:
        """Operator force-close: trust the device immediately (the
        legacy `inject_device_failure(False)` semantics — documented as
        a FORCE; prefer request_probe for verified recovery)."""
        was = self.quarantined
        self.injected = False
        self.breaker.force_close()
        self._sync_latch()
        if was:
            self.num_restores += 1
            self.counters.bump("resilience.backend.restores")

    def probe_now(self, area_link_states, prefix_state) -> Dict[str, object]:
        """Synchronous operator probe (`force_probe` ctrl verb): run one
        device build against the CURRENT LSDB through the full probe
        path (device solve + shadow verification) and report the
        outcome.  A pass restores the device, including from an
        injected quarantine — the operator explicitly demanded a
        re-check."""
        if not area_link_states or not any(
            ls.has_node(self.backend.solver.my_node_name)
            for ls in area_link_states.values()
        ):
            return {"probed": False, "reason": "no LSDB state to probe with"}
        self.injected = False  # the operator overrides the hard latch
        self._forced_probe = True
        self.last_probe = {}
        db = self.backend.build_route_db(
            area_link_states,
            prefix_state,
            force_full=True,
            cache_result=False,
        )
        out: Dict[str, object] = {
            "probed": bool(self.last_probe),
            "restored": not self.quarantined,
            "routes": len(db.unicast_routes) if db is not None else 0,
        }
        out.update(self.last_probe)
        if not self.last_probe:
            # the build never reached the device (algorithm/scale routes
            # every build scalar) — nothing was verified
            out["reason"] = "build took the scalar path; nothing to probe"
            self._forced_probe = False
        return out

    # -- observability -------------------------------------------------------

    def counter_snapshot(self) -> Dict[str, float]:
        """Gauge provider for Monitor.add_counter_provider."""
        out = self.breaker.counter_snapshot("resilience.backend")
        out.update(
            {
                "resilience.backend.quarantined": (
                    1.0 if self.quarantined else 0.0
                ),
                "resilience.backend.injected": 1.0 if self.injected else 0.0,
                "resilience.backend.shadow_checks": float(
                    self.num_shadow_checks
                ),
                "resilience.backend.shadow_mismatches": float(
                    self.num_shadow_mismatches
                ),
                "resilience.backend.quarantines": float(self.num_quarantines),
                "resilience.backend.restores": float(self.num_restores),
                "resilience.backend.dispatch_failures": float(
                    self.num_dispatch_failures
                ),
            }
        )
        return out

    def status(self) -> Dict[str, object]:
        """The ctrl-API `get_resilience_status` device-backend block."""
        return {
            "present": True,
            "quarantined": self.quarantined,
            "injected": self.injected,
            "quarantine_reason": self.quarantine_reason,
            "shadow_sample_every": self.shadow_sample_every,
            "shadow_checks": self.num_shadow_checks,
            "shadow_mismatches": self.num_shadow_mismatches,
            "quarantines": self.num_quarantines,
            "restores": self.num_restores,
            "dispatch_failures": self.num_dispatch_failures,
            "last_probe": dict(self.last_probe),
            "last_mismatch": dict(self.last_mismatch),
            "breaker": self.breaker.status(),
        }
