"""BackendHealthGovernor — owns the device backend's health latch.

The pitch of this system is "replace the trusted scalar Dijkstra with a
batched device kernel" (PAPER §7).  That trade has three failure modes a
production deployment must survive without an operator:

1. **Hard outage** — dispatch raises (chaos ``tpu_fail``, a dead chip, a
   severed tunnel).  Before this module the latch was one-way: only
   chaos flipped ``TpuBackend.device_failed``; an organic dispatch
   exception fell back scalar for THAT build and re-paid the failing
   device on every subsequent rebuild.
2. **Silent data corruption (SDC)** — the kernel returns *wrong but
   plausible* tables (the classic large-fleet accelerator failure mode;
   chaos ``tpu_corrupt`` models it).  Nothing raised, so nothing in the
   old design could notice wrong routes being programmed into FIBs.
3. **Recovery** — once the device heals, something has to notice and
   re-trust it, and it must not re-trust a device that is still lying.

The governor solves all three with ONE mechanism: a
:class:`~openr_tpu.resilience.breaker.CircuitBreaker` around the device,
plus **shadow verification** — a configurable sample of device builds is
recomputed on the native/scalar SPF oracle and RIB-diffed (nexthop sets,
igp cost, plus non-finite/NaN guards on kernel-derived metrics).  A
mismatch or a run of dispatch failures opens the breaker: the backend is
quarantined, ``device_failed`` goes up, and — because
``Decision.device_available()`` reads that latch — route builds, the
serving plane, and what-if queries all degrade to the scalar engines
coherently.  While open, half-open probe builds (which MUST pass shadow
verification) are the only device traffic; a passing probe restores the
device.

The governor is the ONLY writer of ``device_failed`` outside chaos and
the backend itself — enforced statically by orlint's ``resilience-latch``
rule (analysis/passes/resilience_latch.py).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from openr_tpu.common.runtime import Clock, CounterMap, WallClock
from openr_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    CircuitBreaker,
)

#: admit() verdicts
ADMIT_DEVICE = "device"
ADMIT_PROBE = "probe"
ADMIT_QUARANTINED = "quarantined"


class BackendHealthGovernor:
    """Health authority for one TpuBackend.

    The backend calls three hooks around every build:

    * :meth:`admit` — before touching the device.  ``"quarantined"``
      routes the build to the scalar oracle; ``"probe"`` marks this
      build as the half-open probe (it must shadow-verify to restore
      the device); ``"device"`` is the healthy fast path.
    * :meth:`record_dispatch_failure` — a device dispatch raised.
      Consecutive failures past the breaker threshold quarantine.
    * :meth:`after_device_build` — the device produced a RouteDb.
      Sampled builds (and every probe) are shadow-verified against the
      scalar oracle; on mismatch the device is quarantined and the
      *scalar* RouteDb replaces the corrupt device output, so the wrong
      answer never reaches the FIB once detected.
    """

    def __init__(
        self,
        backend,
        clock: Optional[Clock] = None,
        counters: Optional[CounterMap] = None,
        tracer=None,
        shadow_sample_every: int = 8,
        failure_threshold: int = 3,
        probe_backoff_initial_s: float = 1.0,
        probe_backoff_max_s: float = 30.0,
        jitter_pct: float = 0.1,
        seed: int = 0,
        per_device: bool = True,
    ) -> None:
        from openr_tpu.tracing import disabled_tracer

        self.backend = backend
        self.clock = clock if clock is not None else WallClock()
        self.counters = counters if counters is not None else CounterMap()
        self.tracer = tracer if tracer is not None else disabled_tracer()
        self.shadow_sample_every = max(0, int(shadow_sample_every))
        self.breaker = CircuitBreaker(
            "backend",
            self.clock,
            failure_threshold=failure_threshold,
            backoff_initial_s=probe_backoff_initial_s,
            backoff_max_s=probe_backoff_max_s,
            jitter_pct=jitter_pct,
            seed=seed,
            counters=self.counters,
        )
        #: per-chip governance (ISSUE 6): when the backend's DevicePool
        #: has more than one chip, sampled shard outputs are RIB-diffed
        #: per chip and a mismatching chip is quarantined INDIVIDUALLY —
        #: its shard re-packs onto the survivors and it recovers via its
        #: own half-open probed breaker, one chip at a time.  The
        #: whole-backend latch above remains for unattributable faults
        #: and as the "zero healthy chips" degenerate case.
        self.per_device = per_device
        self._breaker_params = dict(
            failure_threshold=failure_threshold,
            backoff_initial_s=probe_backoff_initial_s,
            backoff_max_s=probe_backoff_max_s,
            jitter_pct=jitter_pct,
            seed=seed,
        )
        self._chip_breakers: Dict[int, CircuitBreaker] = {}
        #: chips hard-quarantined by chaos/operator: no probes until the
        #: fault owner requests one (mirror of the aggregate `injected`)
        self._chip_injected: set = set()
        self._chip_reasons: Dict[int, str] = {}
        #: the chip whose half-open probe shard rides the CURRENT build
        #: (at most one per build: chips recover one at a time)
        self._armed_chip_probe: Optional[int] = None
        self.num_chip_quarantines = 0
        self.num_chip_restores = 0
        self.last_chip_mismatch: Dict[str, object] = {}
        #: every mismatching prefix of the last failed shadow check (the
        #: attribution input; reason strings stay first-mismatch-only)
        self._last_mismatch_prefixes: List[str] = []
        #: hard latch: chaos tpu_fail / operator force_quarantine.  While
        #: set, NO probes run (the fault owner declared the device dead);
        #: request_probe() clears it and makes the breaker probe-eligible
        self.injected = False
        self.quarantine_reason = ""
        #: device builds since the last shadow check; starts "due" so the
        #: FIRST device build of a boot is always verified — SDC present
        #: from cold start is caught before the first FIB sync settles
        self._builds_since_check = self.shadow_sample_every
        self._forced_probe = False
        self.num_shadow_checks = 0
        self.num_shadow_mismatches = 0
        self.num_quarantines = 0
        self.num_restores = 0
        self.num_dispatch_failures = 0
        self.last_probe: Dict[str, object] = {}
        self.last_mismatch: Dict[str, object] = {}
        #: quarantine observers (the flight recorder's auto-dump hook):
        #: fired AFTER a quarantine transition settles, with
        #: {"reason", "device"(per-chip) | "devices"(list) | None}
        self._quarantine_listeners: List = []
        self._sync_latch()

    def add_quarantine_listener(self, fn) -> None:
        """Register ``fn(info: dict)`` fired on every quarantine
        transition (whole-backend and per-chip).  Listener exceptions
        are counted, never propagated — an observer must not break the
        health plane it observes."""
        self._quarantine_listeners.append(fn)

    def _notify_quarantine(self, info: Dict[str, object]) -> None:
        for fn in self._quarantine_listeners:
            try:
                fn(dict(info))
            except Exception:  # noqa: BLE001 - observer must not break us
                self.counters.bump("resilience.backend.listener_errors")

    # -- the latch (single writer) ------------------------------------------

    def _raw_pool(self):
        """The backend's DevicePool if it has been built — NEVER builds
        it (pool construction boots jax; latch syncs must stay free)."""
        return getattr(self.backend, "_pool", None)

    def _pool_active(self, pool=None) -> bool:
        pool = pool if pool is not None else self._raw_pool()
        return self.per_device and pool is not None and pool.size > 1

    def _sync_latch(self) -> None:
        pool = self._raw_pool()
        zero_healthy = self._pool_active(pool) and pool.num_healthy == 0
        self.backend.device_failed = (
            self.injected
            or self.breaker.state != STATE_CLOSED
            # the degenerate per-chip case: every chip individually
            # quarantined == the whole device is out, and route builds /
            # serving / what-if degrade coherently through the same latch
            or zero_healthy
        )

    @property
    def quarantined(self) -> bool:
        return self.backend.device_failed

    def _chip_breaker(self, index: int) -> CircuitBreaker:
        br = self._chip_breakers.get(index)
        if br is None:
            br = CircuitBreaker(
                f"backend.dev{index}",
                self.clock,
                counters=self.counters,
                **self._breaker_params,
            )
            self._chip_breakers[index] = br
        return br

    # -- build hooks ---------------------------------------------------------

    def admit(self) -> str:
        """Gate one route build's device usage."""
        self._armed_chip_probe = None
        if self.injected:
            return ADMIT_QUARANTINED
        if self._forced_probe:
            # operator force_probe: run the device + full verification
            # regardless of breaker timing
            self._forced_probe = False
            return ADMIT_PROBE
        if self.breaker.state == STATE_CLOSED:
            pool = self._raw_pool()
            if self._pool_active(pool) and pool.num_healthy == 0:
                # every chip individually quarantined: the only device
                # traffic allowed is a due chip probe (peeked here,
                # consumed when the build plans its dispatch)
                if self._chip_probe_due() is None:
                    return ADMIT_QUARANTINED
                return ADMIT_PROBE
            return ADMIT_DEVICE
        if self.breaker.allow_request():
            return ADMIT_PROBE
        return ADMIT_QUARANTINED

    def _chip_probe_due(self) -> Optional[int]:
        """Lowest-indexed quarantined chip whose hold elapsed (peek —
        does not consume the probe slot); injected chips never probe
        until their fault owner requests it."""
        pool = self._raw_pool()
        if not self._pool_active(pool):
            return None
        now = self.clock.now()
        for k in pool.quarantined_indices():
            if k in self._chip_injected:
                continue
            br = self._chip_breaker(k)
            if br.state == STATE_CLOSED:
                # chip marked unhealthy outside the breaker's view
                # (should not happen; be safe and allow the probe)
                return k
            if br.time_until_probe_s() <= 0.0 and br.state != STATE_HALF_OPEN:
                return k
        return None

    def dispatch_devices(self):
        """(device_indices, probe_device) for one build: the healthy
        chips plus at most ONE quarantined chip whose breaker admits a
        half-open probe shard — chips recover one at a time, and a
        probing chip's output is never served unverified (arming forces
        this build's shadow check).  (None, None) when per-chip
        governance is off (single-chip pool)."""
        pool = self._raw_pool()
        if pool is None:
            pool = getattr(self.backend, "pool", None)
        if not self._pool_active(pool):
            return None, None
        healthy = pool.healthy_indices()
        probe = None
        for k in pool.quarantined_indices():
            if k in self._chip_injected:
                continue
            if self._chip_breaker(k).allow_request():
                probe = k
                self._armed_chip_probe = k
                break
        devices = sorted(healthy + ([probe] if probe is not None else []))
        if not devices:
            return None, None
        return devices, probe

    def confirm_plan(self, devices) -> None:
        """The build settled on its final dispatch set; release an armed
        chip probe that did not make the cut (its shard was dropped, so
        the chip was never exercised — unscored)."""
        chip = self._armed_chip_probe
        if chip is not None and chip not in devices:
            self._chip_breaker(chip).release_probe()
            self._armed_chip_probe = None

    def abort_probe(self) -> None:
        """The admitted probe never reached the device (the build bailed
        to scalar for an eligibility reason, not a health reason):
        release the probe slot without scoring it."""
        self.breaker.release_probe()
        chip = self._armed_chip_probe
        if chip is not None:
            self._chip_breaker(chip).release_probe()
            self._armed_chip_probe = None

    def request_shadow_check(self, reason: str = "") -> None:
        """Make the NEXT device build shadow-verification due regardless
        of where the sampling counter stands.  The warm-rebuild context
        purge calls this: after any event that makes device-resident
        state suspect (corruption injection, quarantine re-pack, a
        full-replace swap), the first build off the purge must be
        verified against the scalar oracle, not merely sampled."""
        self._builds_since_check = self.shadow_sample_every
        self.counters.bump("resilience.backend.shadow_check_requests")

    def record_dispatch_failure(self, exc: Optional[BaseException] = None) -> None:
        """A device dispatch raised (organic failure).  Counts toward the
        breaker threshold; past it the device is quarantined instead of
        being re-tried on every rebuild.  Raises are not attributable to
        one chip (the fetch drains every shard), so they score the
        WHOLE-backend breaker; an armed chip probe is released unscored."""
        chip = self._armed_chip_probe
        if chip is not None:
            self._chip_breaker(chip).release_probe()
            self._armed_chip_probe = None
        self.num_dispatch_failures += 1
        self.counters.bump("resilience.backend.dispatch_failures")
        was_quarantined = self.quarantined
        self.breaker.record_failure()
        self._sync_latch()
        if self.quarantined and not was_quarantined:
            self._note_quarantine(
                f"dispatch:{type(exc).__name__}" if exc is not None else "dispatch"
            )

    def record_stream_failure(
        self, index: int, exc: Optional[BaseException] = None
    ) -> None:
        """ONE chip's streamed shard failed at drain time.  Unlike the
        old all-shard fetch barrier (where a raise was unattributable
        and scored the whole-backend breaker), a streamed completion
        names the failing chip: quarantine IT individually so the
        in-progress build re-packs its rows onto the survivors, and
        leave recovery to the normal per-chip half-open probe cycle —
        no fault owner needs to heal it first."""
        reason = (
            f"stream:{type(exc).__name__}" if exc is not None else "stream"
        )
        self._chip_breaker(index).force_open()
        self._chip_reasons[index] = reason
        self.num_dispatch_failures += 1
        self.counters.bump("resilience.backend.dispatch_failures")
        was = self.quarantined
        pool = self.backend.pool
        if pool.quarantine_device(index):
            self.num_chip_quarantines += 1
            self.counters.bump("resilience.backend.chip_quarantines")
            self._notify_quarantine({"reason": reason, "device": int(index)})
        self._sync_latch()
        if not was and self.quarantined:
            self._note_quarantine(f"device{index}:{reason}")

    def after_device_build(
        self, db, area_link_states, prefix_state, probe: bool = False
    ) -> Tuple[object, bool]:
        """Returns ``(route_db, from_device)``.  ``from_device`` is False
        exactly when shadow verification replaced a corrupt device
        result with the scalar oracle's — the caller must then drop its
        incremental bases."""
        chip_probe = self._armed_chip_probe
        self._builds_since_check += 1
        due = (
            self.shadow_sample_every > 0
            and self._builds_since_check >= self.shadow_sample_every
        )
        if chip_probe is not None:
            # a quarantined chip's probe shard rode this build: its
            # output is in `db` and MUST be verified before serving
            due = True
        if not probe and not due:
            return db, True
        self._builds_since_check = 0
        span = self.tracer.start_span(
            "resilience.probe"
            if (probe or chip_probe is not None)
            else "resilience.shadow_check",
            module="resilience",
            probe=probe or chip_probe is not None,
            device=chip_probe,
        )
        ok, scalar_db, reason = self._shadow_verify(
            db, area_link_states, prefix_state
        )
        self.tracer.end_span(span, passed=ok, reason=reason)
        if probe or chip_probe is not None:
            self.last_probe = {
                "passed": ok,
                "reason": reason,
            }
            if chip_probe is not None:
                self.last_probe["device"] = chip_probe
        self.num_shadow_checks += 1
        self.counters.bump("resilience.backend.shadow_checks")
        if ok:
            was_quarantined = self.quarantined
            if chip_probe is not None:
                self._restore_chip(chip_probe)
            if probe or self.breaker.state != STATE_CLOSED:
                self.breaker.record_success()
                self.injected = False
            self._sync_latch()
            if was_quarantined and not self.quarantined:
                self.num_restores += 1
                self.counters.bump("resilience.backend.restores")
            return db, True
        # wrong-but-plausible device output: quarantine (the one lying
        # chip when the mismatch is attributable to a strict subset of
        # the dispatch set, else the whole backend) AND serve the
        # verified scalar answer for this build
        self.num_shadow_mismatches += 1
        self.counters.bump("resilience.backend.shadow_mismatches")
        self.last_mismatch = {"reason": reason}
        was_quarantined = self.quarantined
        culprits = self._attribute_mismatch()
        if culprits is not None:
            self._quarantine_chips(culprits, chip_probe, reason)
            self._sync_latch()
            if not was_quarantined and self.quarantined:
                # the per-chip quarantine emptied the pool: the
                # degenerate all-chips-out case surfaces on the
                # whole-backend latch like any other outage
                self._note_quarantine(f"shadow:{reason}")
            return scalar_db, False
        if chip_probe is not None:
            # unattributable corruption while a chip was probing: the
            # probe proves nothing either way — released unscored, and
            # the aggregate path below takes over
            self._chip_breaker(chip_probe).release_probe()
            self._armed_chip_probe = None
        if probe and self.breaker.state != STATE_CLOSED:
            self.breaker.record_failure()  # failed probe: backoff doubles
        else:
            # sampled mismatch, or a FORCED probe that failed while the
            # breaker was closed: proven corruption quarantines outright
            self.breaker.force_open()
        self._sync_latch()
        if not was_quarantined:
            self._note_quarantine(f"shadow:{reason}")
        return scalar_db, False

    def _attribute_mismatch(self) -> Optional[List[int]]:
        """Map the failed shadow check's mismatching prefixes onto the
        chips that computed them.  Returns the culprit chip list when
        EVERY mismatching prefix attributes to a chip AND the culprits
        are a strict subset of the chips that produced fresh rows —
        else None (unattributable, or the whole dispatch set lied:
        that is a backend-level fault, exactly the PR-5 semantics)."""
        if not self._pool_active():
            return None
        attribution = self.backend.last_build_attribution()
        if attribution is None:
            return None
        devs_with_rows, dev_of = attribution
        if not self._last_mismatch_prefixes:
            return None
        culprits = set()
        for p in self._last_mismatch_prefixes:
            d = dev_of(p)
            if d is None:
                return None
            culprits.add(d)
        if not culprits:
            return None
        if self._armed_chip_probe is not None:
            # a probing chip caught lying is always individually
            # scoreable, even when it owned every fresh row
            if self._armed_chip_probe in culprits:
                return sorted(culprits)
        if culprits == set(devs_with_rows):
            return None
        return sorted(culprits)

    def _quarantine_chips(
        self, culprits: List[int], chip_probe: Optional[int], reason: str
    ) -> None:
        pool = self.backend.pool
        for k in culprits:
            if chip_probe == k:
                # the probing chip is still lying: its probe failed —
                # backoff doubles, chip stays quarantined
                self._chip_breaker(k).record_failure()
            else:
                self._chip_breaker(k).force_open()
            if pool.quarantine_device(k):
                self.num_chip_quarantines += 1
                self.counters.bump("resilience.backend.chip_quarantines")
            self._chip_reasons[k] = f"shadow:{reason}"
        self.last_chip_mismatch = {
            "devices": list(culprits),
            "reason": reason,
        }
        for k in culprits:
            self._notify_quarantine(
                {
                    "reason": f"shadow:{reason}",
                    "device": int(k),
                    "devices": [int(c) for c in culprits],
                }
            )
        if chip_probe is not None and chip_probe not in culprits:
            # the probing chip's shard verified clean in this full RIB
            # check even though another chip was caught lying: that IS a
            # passed shadow-verified probe — restore it
            self._restore_chip(chip_probe)
        self._armed_chip_probe = None

    def _restore_chip(self, index: int) -> None:
        pool = self.backend.pool
        self._chip_breaker(index).record_success()
        self._chip_injected.discard(index)
        self._chip_reasons.pop(index, None)
        if pool.restore_device(index):
            self.num_chip_restores += 1
            self.counters.bump("resilience.backend.chip_restores")
        if self._armed_chip_probe == index:
            self._armed_chip_probe = None

    def _note_quarantine(self, reason: str) -> None:
        self.quarantine_reason = reason
        self.num_quarantines += 1
        self.counters.bump("resilience.backend.quarantines")
        self._notify_quarantine({"reason": reason, "device": None})

    # -- shadow verification -------------------------------------------------

    def _shadow_verify(
        self, device_db, area_link_states, prefix_state
    ) -> Tuple[bool, object, str]:
        """Device RouteDb vs the scalar oracle: (ok, scalar_db, reason).

        Checks, cheapest first: non-finite guard on kernel-derived
        metrics (NaN/inf igp_cost is *never* legitimate on a reachable
        route), then the full RIB diff — same prefix set, and per prefix
        the same nexthop set (address/iface/metric/area) and igp cost.
        The scalar db is computed ONCE and returned so a mismatching
        build can be served from it without a second solve.  EVERY
        mismatching prefix is collected (``_last_mismatch_prefixes``) —
        per-chip attribution needs the complete culprit set, not just
        the first lie found — while the reason string stays the first
        mismatch for readable status output."""
        self._last_mismatch_prefixes = []
        non_finite = [
            prefix
            for prefix, entry in device_db.unicast_routes.items()
            if not math.isfinite(entry.igp_cost)
        ]
        if non_finite:
            self._last_mismatch_prefixes = non_finite
            return False, self._scalar_db(area_link_states, prefix_state), (
                f"non_finite:{non_finite[0]}"
            )
        scalar_db = self._scalar_db(area_link_states, prefix_state)
        dev = device_db.unicast_routes
        ref = scalar_db.unicast_routes
        bad: List[str] = []
        reason = ""
        if set(dev) != set(ref):
            missing = sorted(set(ref) - set(dev))
            extra = sorted(set(dev) - set(ref))
            bad.extend(missing + extra)
            reason = (
                f"prefix_set:missing={missing[:3]}:extra={extra[:3]}"
            )
        for prefix, d in dev.items():
            r = ref.get(prefix)
            if r is None:
                continue  # already in `bad` via the prefix-set diff
            if set(d.nexthops) != set(r.nexthops):
                bad.append(prefix)
                reason = reason or f"nexthops:{prefix}"
            elif float(d.igp_cost) != float(r.igp_cost):
                bad.append(prefix)
                reason = reason or f"igp_cost:{prefix}"
            elif d.do_not_install != r.do_not_install:
                bad.append(prefix)
                reason = reason or f"do_not_install:{prefix}"
        if bad:
            self._last_mismatch_prefixes = bad
            return False, scalar_db, reason
        return True, scalar_db, ""

    def _scalar_db(self, area_link_states, prefix_state):
        return self.backend.solver.build_route_db(
            area_link_states, prefix_state
        )

    # -- operator / chaos controls -------------------------------------------

    def force_quarantine(self, reason: str = "operator") -> None:
        """Hard-quarantine the device (chaos tpu_fail inject, operator
        drain).  No probes run until request_probe/force_restore."""
        was = self.quarantined
        self.injected = True
        self.breaker.force_open()
        self._sync_latch()
        if not was:
            self._note_quarantine(reason)
        else:
            self.quarantine_reason = reason

    def request_probe(self, reason: str = "heal") -> None:
        """The fault owner healed the device: clear the hard latch and
        make the breaker probe-eligible NOW.  The device stays
        quarantined until a probe build passes shadow verification —
        heals are *probed*, never trusted blindly."""
        self.injected = False
        self.breaker.expire_hold()
        self.counters.bump("resilience.backend.probe_requests")
        self._sync_latch()

    def force_restore(self, reason: str = "operator") -> None:
        """Operator force-close: trust the device immediately (the
        legacy `inject_device_failure(False)` semantics — documented as
        a FORCE; prefer request_probe for verified recovery)."""
        was = self.quarantined
        self.injected = False
        self.breaker.force_close()
        self._sync_latch()
        if was:
            self.num_restores += 1
            self.counters.bump("resilience.backend.restores")

    # -- per-chip controls (chaos tpu_fail(device_index=...), operator) ----

    def resolve_device_index(self, index: int) -> Optional[int]:
        """Requested chip index → pool index (modulo the pool size so
        seeded plans stay meaningful on any device count); None when
        per-chip governance is inactive (single-chip pool or
        per_device=False) — callers fall back to the whole-backend
        latch."""
        pool = self.backend.pool
        if not self._pool_active(pool):
            return None
        return int(index) % pool.size

    def force_quarantine_device(self, index: int, reason: str = "operator") -> None:
        """Hard-quarantine ONE chip: its shard re-packs onto the
        survivors from the next build on, and no probes run on it until
        its fault owner requests one.  The whole-backend latch only
        trips when this empties the pool (zero healthy chips)."""
        pool = self.backend.pool
        was = self.quarantined
        self._chip_breaker(index).force_open()
        self._chip_injected.add(index)
        self._chip_reasons[index] = reason
        if pool.quarantine_device(index):
            self.num_chip_quarantines += 1
            self.counters.bump("resilience.backend.chip_quarantines")
            self._notify_quarantine(
                {"reason": reason, "device": int(index)}
            )
        self._sync_latch()
        if not was and self.quarantined:
            self._note_quarantine(f"device{index}:{reason}")

    def request_probe_device(self, index: int, reason: str = "heal") -> None:
        """The fault owner healed chip ``index``: clear its hard latch
        and make its breaker probe-eligible NOW.  The chip stays
        quarantined until its probe shard passes shadow verification —
        chip heals are probed, never trusted blindly."""
        self._chip_injected.discard(index)
        self._chip_breaker(index).expire_hold()
        self.counters.bump("resilience.backend.chip_probe_requests")
        self._sync_latch()

    def force_restore_device(self, index: int, reason: str = "operator") -> None:
        """Operator force-close for one chip (unverified; prefer
        request_probe_device for probed recovery)."""
        self._chip_injected.discard(index)
        self._chip_reasons.pop(index, None)
        self._chip_breaker(index).force_close()
        if self.backend.pool.restore_device(index):
            self.num_chip_restores += 1
            self.counters.bump("resilience.backend.chip_restores")
        self._sync_latch()

    def probe_now(
        self,
        area_link_states,
        prefix_state,
        device_index: Optional[int] = None,
    ) -> Dict[str, object]:
        """Synchronous operator probe (`force_probe` ctrl verb): run one
        device build against the CURRENT LSDB through the full probe
        path (device solve + shadow verification) and report the
        outcome.  A pass restores the device, including from an
        injected quarantine — the operator explicitly demanded a
        re-check.  With ``device_index``, the probe targets ONE chip: a
        quarantined chip gets its breaker hold expired so the build
        carries its probe shard; a healthy chip rides a fully-verified
        forced build."""
        if not area_link_states or not any(
            ls.has_node(self.backend.solver.my_node_name)
            for ls in area_link_states.values()
        ):
            return {"probed": False, "reason": "no LSDB state to probe with"}
        if device_index is not None:
            pool = self.backend.pool
            if not (0 <= device_index < pool.size):
                return {
                    "probed": False,
                    "reason": (
                        f"no device {device_index} in the pool "
                        f"(size {pool.size})"
                    ),
                }
            if not self._pool_active(pool):
                return {
                    "probed": False,
                    "reason": "per-device governance inactive "
                    "(single-chip pool or per_device=False)",
                }
            if pool.is_healthy(device_index):
                self._forced_probe = True  # full verified build
            else:
                self.request_probe_device(device_index, reason="operator")
        else:
            self.injected = False  # the operator overrides the hard latch
            self._forced_probe = True
        self.last_probe = {}
        db = self.backend.build_route_db(
            area_link_states,
            prefix_state,
            force_full=True,
            cache_result=False,
        )
        out: Dict[str, object] = {
            "probed": bool(self.last_probe),
            "restored": (
                # a chip probe reports THAT CHIP's health, not the
                # whole-backend latch (which a single drained chip
                # never raised in the first place)
                self.backend.pool.is_healthy(device_index)
                if device_index is not None
                else not self.quarantined
            ),
            "routes": len(db.unicast_routes) if db is not None else 0,
        }
        if device_index is not None:
            out["device"] = device_index
        out.update(self.last_probe)
        if not self.last_probe:
            # the build never reached the device (algorithm/scale routes
            # every build scalar) — nothing was verified
            out["reason"] = "build took the scalar path; nothing to probe"
            self._forced_probe = False
        return out

    # -- observability -------------------------------------------------------

    def counter_snapshot(self) -> Dict[str, float]:
        """Gauge provider for Monitor.add_counter_provider."""
        out = self.breaker.counter_snapshot("resilience.backend")
        out.update(
            {
                "resilience.backend.quarantined": (
                    1.0 if self.quarantined else 0.0
                ),
                "resilience.backend.injected": 1.0 if self.injected else 0.0,
                "resilience.backend.shadow_checks": float(
                    self.num_shadow_checks
                ),
                "resilience.backend.shadow_mismatches": float(
                    self.num_shadow_mismatches
                ),
                "resilience.backend.quarantines": float(self.num_quarantines),
                "resilience.backend.restores": float(self.num_restores),
                "resilience.backend.dispatch_failures": float(
                    self.num_dispatch_failures
                ),
                "resilience.backend.chip_quarantines": float(
                    self.num_chip_quarantines
                ),
                "resilience.backend.chip_restores": float(
                    self.num_chip_restores
                ),
            }
        )
        for k in sorted(self._chip_breakers):
            out.update(
                self._chip_breakers[k].counter_snapshot(
                    f"resilience.backend.dev{k}"
                )
            )
        pool = self._raw_pool()
        if pool is not None:
            out["resilience.backend.pool_size"] = float(pool.size)
            out["resilience.backend.healthy_devices"] = float(
                pool.num_healthy
            )
        return out

    def status(self) -> Dict[str, object]:
        """The ctrl-API `get_resilience_status` device-backend block."""
        out = {
            "present": True,
            "quarantined": self.quarantined,
            "injected": self.injected,
            "quarantine_reason": self.quarantine_reason,
            "shadow_sample_every": self.shadow_sample_every,
            "shadow_checks": self.num_shadow_checks,
            "shadow_mismatches": self.num_shadow_mismatches,
            "quarantines": self.num_quarantines,
            "restores": self.num_restores,
            "dispatch_failures": self.num_dispatch_failures,
            "last_probe": dict(self.last_probe),
            "last_mismatch": dict(self.last_mismatch),
            "breaker": self.breaker.status(),
            "per_device": self.per_device,
            "chip_quarantines": self.num_chip_quarantines,
            "chip_restores": self.num_chip_restores,
            "last_chip_mismatch": dict(self.last_chip_mismatch),
        }
        pool = self._raw_pool()
        if pool is not None:
            # per-chip rows (the `breeze resilience status` device table);
            # the pool is reported only once something built it — status
            # queries must never be the thing that boots jax
            out["pool"] = {
                "size": pool.size,
                "num_healthy": pool.num_healthy,
            }
            out["devices"] = [
                {
                    "device": k,
                    "healthy": pool.is_healthy(k),
                    "injected": k in self._chip_injected,
                    "reason": self._chip_reasons.get(k, ""),
                    "breaker": (
                        self._chip_breakers[k].status()
                        if k in self._chip_breakers
                        else None
                    ),
                }
                for k in range(pool.size)
            ]
        return out
