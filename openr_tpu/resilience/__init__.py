"""openr_tpu.resilience — the shared recovery plane.

One :class:`CircuitBreaker` state machine (closed → open → half-open,
jittered exponential hold, single-probe exclusion) protects every
external-dependency edge the daemon has — the device backend (via
:class:`BackendHealthGovernor`, which adds shadow verification against
the scalar SPF oracle so silently-wrong kernel output is caught, not
just raised errors), the FIB agent retry path (fib/fib.py), and KvStore
peer transport sessions (kvstore/transport.py) — under one gauge schema
(``resilience.*``) and one tracing story (``resilience.probe`` spans).

Operator surface: ctrl verbs ``get_resilience_status`` /
``force_quarantine`` / ``force_probe``, `breeze resilience status`, and
`EmulatedNetwork.resilience_status()`.  See docs/Robustness.md.
"""

from __future__ import annotations

from typing import Dict

from openr_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    CircuitBreaker,
)
from openr_tpu.resilience.governor import BackendHealthGovernor


def node_resilience_status(node) -> Dict[str, object]:
    """The `get_resilience_status` payload for one OpenrNode — shared by
    the ctrl handler and EmulatedNetwork so the two views can't drift."""
    backend = getattr(node.decision, "backend", None)
    gov = getattr(backend, "governor", None)
    out: Dict[str, object] = {
        "node": node.name,
        "device_backend": (
            gov.status() if gov is not None else {"present": False}
        ),
        "fib_agent": (
            node.fib.breaker.status()
            if getattr(node.fib, "breaker", None) is not None
            else {}
        ),
    }
    kv = getattr(node, "kv_transport", None)
    if kv is not None and hasattr(kv, "breaker_status"):
        out["kv_transport"] = kv.breaker_status()
    if hasattr(backend, "_warm_class_builds"):
        # warm-rebuild health split by delta class (ISSUE 12): during a
        # rolling fleet upgrade the STRUCTURAL ratio is the first thing
        # an operator reads — a collapse there means publication→FIB
        # is back on the cold wall while the fleet churns
        builds = backend._warm_class_builds
        fallbacks = backend._warm_class_fallbacks
        out["warm"] = {
            "enabled": bool(backend._warm_enabled),
            "context_ready": backend._warm_ctx is not None,
            "by_class": {
                cls: {
                    "hits": builds[cls],
                    "fallbacks": fallbacks[cls],
                    "hit_ratio": round(
                        builds[cls]
                        / max(1, builds[cls] + fallbacks[cls]),
                        3,
                    ),
                    "fallback_reasons": dict(
                        sorted(
                            backend._warm_class_fallback_reasons[
                                cls
                            ].items()
                        )
                    ),
                }
                for cls in sorted(builds)
            },
            "encode_patches": backend.num_encode_patches,
            "encode_slot_patches": backend.num_encode_slot_patches,
            "slot_declines": dict(
                sorted(backend._slot_decline_reasons.items())
            ),
            "purges": backend.num_warm_purges,
            "purge_reasons": dict(
                sorted(backend._warm_purge_reasons.items())
            ),
        }
    return out


__all__ = [
    "CircuitBreaker",
    "BackendHealthGovernor",
    "node_resilience_status",
    "STATE_CLOSED",
    "STATE_OPEN",
    "STATE_HALF_OPEN",
]
