"""CircuitBreaker — ONE state machine for every external-dependency edge.

The daemon leans on three things it does not control: the accelerator
(device kernel dispatch), the FIB agent (platform RPC), and KvStore peer
sessions (network RPC).  Before this module each edge had its own ad-hoc
recovery idiom — a one-way boolean latch for the device, a raw
:class:`~openr_tpu.common.utils.ExponentialBackoff` for the agent,
drop-and-redial for peers — with three different counter vocabularies
and three different failure semantics.  This breaker is the shared
primitive: closed → open → half-open, jittered exponential backoff on
the open hold, single-probe exclusion in half-open, and one gauge schema
(``resilience.<name>.*``) so `breeze resilience status` reads every edge
the same way.

Design constraints (the same ones as everything else in this repo):

* **Clock-injected** — all timing through the shared :class:`Clock`, so
  SimClock chaos tests replay the full open→probe→close trajectory in
  virtual time, deterministically.
* **Deterministic jitter** — the jitter draw comes from a
  ``random.Random`` seeded from ``(seed, crc32(name))``, never from the
  process hash seed or wall entropy; two runs from one seed produce
  byte-identical counter dumps (the chaos reproducibility contract).
  Jitter exists so a fleet of breakers opened by one shared outage does
  not re-probe in lockstep (thundering-herd on the healing dependency).
* **Probe exclusion** — in half-open exactly ONE caller wins the probe
  slot (`allow_request` returns True once); everyone else keeps getting
  short-circuited until the probe resolves via `record_success` /
  `record_failure`.
"""

from __future__ import annotations

import random
import zlib
from typing import Dict, Optional

from openr_tpu.common.runtime import Clock, CounterMap

#: state gauge encoding (resilience.<name>.state)
STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

_STATE_GAUGE = {STATE_CLOSED: 0.0, STATE_OPEN: 1.0, STATE_HALF_OPEN: 2.0}


class CircuitBreaker:
    """closed → open → half-open breaker with jittered exponential hold.

    * ``record_failure()`` — one observed failure of the protected
      dependency.  ``failure_threshold`` consecutive failures (or a
      failed half-open probe, or ``force_open``) open the breaker.
    * ``allow_request()`` — admission gate.  Closed: always True.
      Open: False until the jittered hold elapses, then the FIRST caller
      transitions to half-open and owns the probe (True); subsequent
      callers stay short-circuited.
    * ``record_success()`` — closes from any state and resets the
      backoff ladder.
    """

    def __init__(
        self,
        name: str,
        clock: Clock,
        failure_threshold: int = 3,
        backoff_initial_s: float = 1.0,
        backoff_max_s: float = 30.0,
        jitter_pct: float = 0.1,
        seed: int = 0,
        counters: Optional[CounterMap] = None,
    ) -> None:
        assert failure_threshold >= 1
        assert 0 < backoff_initial_s <= backoff_max_s
        assert 0.0 <= jitter_pct < 1.0
        self.name = name
        self.clock = clock
        self.failure_threshold = failure_threshold
        self.backoff_initial_s = backoff_initial_s
        self.backoff_max_s = backoff_max_s
        self.jitter_pct = jitter_pct
        #: name-salted so a fleet of same-seed breakers still de-syncs;
        #: crc32 (NOT hash()) keeps the draw independent of the process
        #: hash seed — reproducibility across interpreter invocations
        self._rng = random.Random((seed << 32) ^ zlib.crc32(name.encode()))
        self.counters = counters if counters is not None else CounterMap()
        self.state = STATE_CLOSED
        self._consecutive_failures = 0
        #: doublings applied so far on the open hold (resets on close)
        self._open_streak = 0
        #: jittered hold actually drawn for the current open period (s)
        self._hold_s = 0.0
        self._probe_due_at = 0.0
        self._probe_in_flight = False
        self.num_opens = 0
        self.num_closes = 0
        self.num_probes = 0
        self.num_probe_failures = 0
        self.num_failures = 0
        self.num_successes = 0
        self.num_short_circuits = 0

    # -- transitions --------------------------------------------------------

    def _draw_hold_s(self) -> float:
        base = min(
            self.backoff_initial_s * (2 ** self._open_streak),
            self.backoff_max_s,
        )
        if self.jitter_pct:
            base *= 1.0 + self.jitter_pct * self._rng.uniform(-1.0, 1.0)
        return base

    def _open(self) -> None:
        self.state = STATE_OPEN
        self._probe_in_flight = False
        self._hold_s = self._draw_hold_s()
        self._open_streak += 1
        self._probe_due_at = self.clock.now() + self._hold_s
        self.num_opens += 1
        self.counters.bump(f"resilience.{self.name}.opens")

    def _close(self) -> None:
        if self.state != STATE_CLOSED:
            self.num_closes += 1
            self.counters.bump(f"resilience.{self.name}.closes")
        self.state = STATE_CLOSED
        self._consecutive_failures = 0
        self._open_streak = 0
        self._hold_s = 0.0
        self._probe_in_flight = False

    def force_open(self) -> None:
        """Quarantine now, regardless of the failure count (operator
        drain, chaos injection, shadow-verification mismatch)."""
        self._consecutive_failures = max(
            self._consecutive_failures, self.failure_threshold
        )
        self._open()

    def force_close(self) -> None:
        """Operator force-restore: trust the dependency immediately."""
        self._close()

    def expire_hold(self) -> None:
        """Make the probe due NOW (the healed-fault fast path: a chaos
        heal or operator `force_probe` should not wait out the remaining
        jittered hold)."""
        if self.state == STATE_OPEN:
            self._probe_due_at = self.clock.now()

    def release_probe(self) -> None:
        """The half-open probe owner never exercised the dependency
        (its admitted work bailed for an unrelated reason): return to
        open with the probe slot immediately re-available, unscored."""
        if self.state == STATE_HALF_OPEN:
            self.state = STATE_OPEN
            self._probe_in_flight = False
            self._probe_due_at = self.clock.now()

    # -- admission ----------------------------------------------------------

    def allow_request(self) -> bool:
        """Gate one unit of work against the protected dependency.
        Returns False when the caller must short-circuit (breaker open,
        hold not elapsed, or another probe already in flight)."""
        if self.state == STATE_CLOSED:
            return True
        if self.state == STATE_OPEN and self.clock.now() >= self._probe_due_at:
            self.state = STATE_HALF_OPEN
            self._probe_in_flight = True
            self.num_probes += 1
            self.counters.bump(f"resilience.{self.name}.probes")
            return True  # this caller IS the probe
        self.num_short_circuits += 1
        self.counters.bump(f"resilience.{self.name}.short_circuits")
        return False

    # -- outcomes ------------------------------------------------------------

    def record_success(self) -> None:
        self.num_successes += 1
        self._close()

    def record_failure(self) -> None:
        self.num_failures += 1
        self.counters.bump(f"resilience.{self.name}.failures")
        if self.state == STATE_HALF_OPEN:
            # the probe failed: back off harder
            self.num_probe_failures += 1
            self.counters.bump(f"resilience.{self.name}.probe_failures")
            self._open()
            return
        if self.state == STATE_OPEN:
            return  # already quarantined; nothing to escalate
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.failure_threshold:
            self._open()

    # -- introspection -------------------------------------------------------

    def current_hold_s(self) -> float:
        return self._hold_s

    def time_until_probe_s(self) -> float:
        if self.state != STATE_OPEN:
            return 0.0
        return max(0.0, self._probe_due_at - self.clock.now())

    def counter_snapshot(self, prefix: Optional[str] = None) -> Dict[str, float]:
        """Gauge surface for Monitor.add_counter_provider — the ONE
        schema every breaker-protected edge shares."""
        p = prefix if prefix is not None else f"resilience.{self.name}"
        return {
            f"{p}.state": _STATE_GAUGE[self.state],
            f"{p}.opens": float(self.num_opens),
            f"{p}.closes": float(self.num_closes),
            f"{p}.probes": float(self.num_probes),
            f"{p}.probe_failures": float(self.num_probe_failures),
            f"{p}.failures": float(self.num_failures),
            f"{p}.successes": float(self.num_successes),
            f"{p}.short_circuits": float(self.num_short_circuits),
            f"{p}.hold_ms": self._hold_s * 1000.0,
        }

    def status(self) -> Dict[str, object]:
        """The ctrl-API `get_resilience_status` wire form."""
        return {
            "state": self.state,
            "consecutive_failures": self._consecutive_failures,
            "hold_ms": round(self._hold_s * 1000.0, 3),
            "time_until_probe_ms": round(
                self.time_until_probe_s() * 1000.0, 3
            ),
            "opens": self.num_opens,
            "closes": self.num_closes,
            "probes": self.num_probes,
            "probe_failures": self.num_probe_failures,
            "failures": self.num_failures,
            "successes": self.num_successes,
            "short_circuits": self.num_short_circuits,
        }
