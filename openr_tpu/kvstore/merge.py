"""mergeKeyValues — the eventual-consistency conflict-resolution core.

Faithful port of openr/kvstore/KvStoreUtil.cpp:253-520 (getMergeType,
mergeKeyValues, compareValues).  This is the second hot path after SPF
(SURVEY §3.2) and is deliberately dependency-free so the C++ native
implementation (openr_tpu/native) can mirror it 1:1.
"""

from __future__ import annotations

import enum
import hashlib
from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional, Tuple

from openr_tpu import constants as C
from openr_tpu.types import KvStoreNoMergeReason, Value


def generate_hash(value: Value) -> int:
    """Stable 63-bit digest of (version, originatorId, value)
    (reference generateHash in LsdbUtil)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(str(value.version).encode())
    h.update(b"|")
    h.update(value.originator_id.encode())
    h.update(b"|")
    if value.value is not None:
        h.update(value.value)
    return int.from_bytes(h.digest(), "big") & 0x7FFF_FFFF_FFFF_FFFF


def is_valid_ttl(ttl: int) -> bool:
    return ttl == C.TTL_INFINITY or ttl > 0


def is_ttl_update(value: Value) -> bool:
    """A value-less update only refreshes the TTL
    (KvStoreUtil.cpp:104-106)."""
    return value.value is None


class ComparisonResult(enum.IntEnum):
    TIED = 0
    FIRST = 1
    SECOND = 2
    UNKNOWN = 3


def compare_values(v1: Value, v2: Value) -> ComparisonResult:
    """Which value wins? (KvStoreUtil.cpp:470-520)."""
    if v1.version != v2.version:
        return (
            ComparisonResult.FIRST
            if v1.version > v2.version
            else ComparisonResult.SECOND
        )
    if v1.originator_id != v2.originator_id:
        return (
            ComparisonResult.FIRST
            if v1.originator_id > v2.originator_id
            else ComparisonResult.SECOND
        )
    if v1.hash is not None and v2.hash is not None and v1.hash == v2.hash:
        if v1.ttl_version != v2.ttl_version:
            return (
                ComparisonResult.FIRST
                if v1.ttl_version > v2.ttl_version
                else ComparisonResult.SECOND
            )
        return ComparisonResult.TIED
    if v1.value is not None and v2.value is not None:
        if v1.value > v2.value:
            return ComparisonResult.FIRST
        if v1.value < v2.value:
            return ComparisonResult.SECOND
        return ComparisonResult.TIED
    return ComparisonResult.UNKNOWN


class MergeType(enum.IntEnum):
    NO_UPDATE_NEEDED = 0
    UPDATE_ALL_NEEDED = 1
    UPDATE_TTL_NEEDED = 2
    RESYNC_NEEDED = 3


def _get_merge_type(
    key: str,
    value: Value,
    store: Dict[str, Value],
    sender: Optional[str],
) -> Tuple[MergeType, Optional[KvStoreNoMergeReason]]:
    """KvStoreUtil.cpp:253-378."""
    existing = store.get(key)
    my_version = existing.version if existing is not None else C.UNDEFINED_VERSION

    if is_ttl_update(value):
        # inconsistency: ttl update for a key we don't have, or with a
        # different (version, originator) (isResyncNeeded,
        # KvStoreUtil.cpp:133-200).  Triggers resync only when the sender IS
        # the originator.
        inconsistent = (
            existing is None
            or value.version != existing.version
            or value.originator_id != existing.originator_id
        )
        if inconsistent:
            if (sender or "") == value.originator_id:
                return MergeType.RESYNC_NEEDED, (
                    KvStoreNoMergeReason.INCONSISTENCY_DETECTED
                )
            return MergeType.NO_UPDATE_NEEDED, KvStoreNoMergeReason.NO_MATCHED_KEY
        if value.ttl_version > existing.ttl_version:
            return MergeType.UPDATE_TTL_NEEDED, None
        return MergeType.NO_UPDATE_NEEDED, KvStoreNoMergeReason.NO_NEED_TO_UPDATE

    # value-carrying update
    if not (value.version > 0 and value.version >= my_version):
        return MergeType.NO_UPDATE_NEEDED, KvStoreNoMergeReason.OLD_VERSION
    if value.version > my_version:
        return MergeType.UPDATE_ALL_NEEDED, None
    assert existing is not None
    if value.originator_id > existing.originator_id:
        return MergeType.UPDATE_ALL_NEEDED, None
    if value.originator_id == existing.originator_id:
        # same version + originator: larger value wins; equal value falls
        # through to ttlVersion
        assert existing.value is not None, "stored value must carry data"
        if value.value > existing.value:
            return MergeType.UPDATE_ALL_NEEDED, None
        if value.value == existing.value:
            if value.ttl_version > existing.ttl_version:
                return MergeType.UPDATE_TTL_NEEDED, None
            return (
                MergeType.NO_UPDATE_NEEDED,
                KvStoreNoMergeReason.NO_NEED_TO_UPDATE,
            )
    return MergeType.NO_UPDATE_NEEDED, KvStoreNoMergeReason.NO_NEED_TO_UPDATE


@dataclass
class MergeResult:
    """KvStoreMergeResult (KvStore.thrift:195-199)."""

    key_vals: Dict[str, Value] = field(default_factory=dict)  # to flood
    no_merge_reasons: Dict[str, KvStoreNoMergeReason] = field(default_factory=dict)
    inconsistency_detected_with_originator: bool = False


def merge_key_values(
    store: Dict[str, Value],
    key_vals: Dict[str, Value],
    sender: Optional[str] = None,
    key_filter=None,
) -> MergeResult:
    """Merge incoming key-vals into `store` in place; returns the accepted
    delta (to announce/flood) and per-key rejection reasons
    (KvStoreUtil.cpp:391-466).

    Keys merge in SORTED order, not arrival order: the accepted delta's
    iteration order becomes the flooded publication's wire order, and
    arrival order is an accident of the sender's dict construction —
    two stores merging the same facts must flood the same bytes
    (orlint unordered-emission; regression: tests/test_kvstore_merge.py
    canonical-flood-order test)."""
    result = MergeResult()
    for key, value in sorted(key_vals.items()):
        if key_filter is not None and not key_filter(key, value):
            result.no_merge_reasons[key] = KvStoreNoMergeReason.NO_MATCHED_KEY
            continue
        if not is_valid_ttl(value.ttl):
            result.no_merge_reasons[key] = KvStoreNoMergeReason.INVALID_TTL
            continue
        merge_type, reason = _get_merge_type(key, value, store, sender)
        if merge_type == MergeType.RESYNC_NEEDED:
            result.inconsistency_detected_with_originator = True
            result.no_merge_reasons[key] = (
                KvStoreNoMergeReason.INCONSISTENCY_DETECTED
            )
            continue
        if merge_type == MergeType.NO_UPDATE_NEEDED:
            if reason is not None:
                result.no_merge_reasons[key] = reason
            continue
        if merge_type == MergeType.UPDATE_ALL_NEEDED:
            stored = Value(
                version=value.version,
                originator_id=value.originator_id,
                value=value.value,
                ttl=value.ttl,
                ttl_version=value.ttl_version,
                hash=value.hash if value.hash is not None else generate_hash(value),
            )
            store[key] = stored
        else:  # UPDATE_TTL_NEEDED
            existing = store[key]
            existing.ttl = value.ttl
            existing.ttl_version = value.ttl_version
        result.key_vals[key] = value
    return result


def dump_hashes(
    store: Dict[str, Value], keys: Optional[Iterable[str]] = None
) -> Dict[str, Tuple[int, str, Optional[int]]]:
    """(version, originatorId, hash) digests for full-sync
    (dumpHashWithFilters)."""
    src = keys if keys is not None else store.keys()
    return {
        k: (store[k].version, store[k].originator_id, store[k].hash)
        for k in src
        if k in store
    }
