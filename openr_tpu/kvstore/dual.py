"""DUAL — Diffusing Update Algorithm forming the KvStore flood topology.

Re-implementation of the reference's flood-optimization library
(openr/kvstore/Dual.{h,cpp}; protocol spec in
docs/Features/FloodOptimization.md; algorithm per Garcia-Luna-Aceves,
"Loop-Free Routing Using Diffusing Computations").  Each node runs one
`Dual` computation per discovered root; all nodes converge on a spanning
tree (SPT) rooted at the smallest-named root with a valid route, and
KvStore floods publications only to its SPT parent + children, reducing
flood complexity from O(E) to O(V).

State per (node, root):
  * distance / report-distance / feasible-distance — classic DUAL triplet
  * a five-state machine PASSIVE / ACTIVE0..3 (Dual.h:27-35)
  * per-neighbor report-distance, expect-reply, need-to-reply
  * `cornet` — stack of pending queries awaiting our reply

Messages (if/Dual.thrift): UPDATE (report-distance change), QUERY (start
a diffusing computation), REPLY (diffusing ack).  All emission is
collected into a `MsgBatch` (neighbor -> DualMessages) so the caller owns
I/O; `DualNode` subclasses plug in the wire.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

INF = 2**63 - 1  # int64 max == "unreachable" (reference uses INT64_MAX)


class DualState(enum.Enum):
    PASSIVE = "PASSIVE"
    ACTIVE0 = "ACTIVE0"
    ACTIVE1 = "ACTIVE1"
    ACTIVE2 = "ACTIVE2"
    ACTIVE3 = "ACTIVE3"


class DualEvent(enum.Enum):
    QUERY_FROM_SUCCESSOR = "QUERY_FROM_SUCCESSOR"
    LAST_REPLY = "LAST_REPLY"
    INCREASE_D = "INCREASE_D"
    OTHERS = "OTHERS"


class DualMessageType(enum.Enum):
    UPDATE = 1
    QUERY = 2
    REPLY = 3


@dataclass
class DualMessage:
    """One DUAL PDU (if/Dual.thrift DualMessage)."""

    dst_id: str  # root the message concerns
    distance: int
    type: DualMessageType


@dataclass
class DualMessages:
    """Batch of PDUs from one sender (if/Dual.thrift DualMessages)."""

    src_id: str
    messages: List[DualMessage] = field(default_factory=list)

    def to_wire(self) -> dict:
        return {
            "src_id": self.src_id,
            "messages": [
                {"dst_id": m.dst_id, "distance": m.distance,
                 "type": m.type.value}
                for m in self.messages
            ],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "DualMessages":
        return cls(
            src_id=d["src_id"],
            messages=[
                DualMessage(
                    dst_id=m["dst_id"],
                    distance=m["distance"],
                    type=DualMessageType(m["type"]),
                )
                for m in d.get("messages", [])
            ],
        )


#: neighbor-id -> messages accumulated for it during one event
MsgBatch = Dict[str, List[DualMessage]]


@dataclass
class DualPerRootCounters:
    query_sent: int = 0
    query_recv: int = 0
    reply_sent: int = 0
    reply_recv: int = 0
    update_sent: int = 0
    update_recv: int = 0
    total_sent: int = 0
    total_recv: int = 0


def _add(d1: int, d2: int) -> int:
    """Saturating distance addition."""
    return INF if (d1 == INF or d2 == INF) else d1 + d2


class DualStateMachine:
    """The five-state DUAL FSM (Dual.cpp:15-62; states per the
    Cornell/lunes93 paper).  `fc` = feasible condition held."""

    def __init__(self) -> None:
        self.state = DualState.PASSIVE

    def process_event(self, event: DualEvent, fc: bool = True) -> None:
        s, E = self.state, DualEvent
        if s == DualState.PASSIVE:
            if not fc:
                self.state = (
                    DualState.ACTIVE3
                    if event == E.QUERY_FROM_SUCCESSOR
                    else DualState.ACTIVE1
                )
        elif s == DualState.ACTIVE0:
            if event == E.LAST_REPLY:
                self.state = DualState.PASSIVE if fc else DualState.ACTIVE2
        elif s == DualState.ACTIVE1:
            if event == E.INCREASE_D:
                self.state = DualState.ACTIVE0
            elif event == E.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif event == E.QUERY_FROM_SUCCESSOR:
                self.state = DualState.ACTIVE2
        elif s == DualState.ACTIVE2:
            if event == E.LAST_REPLY:
                self.state = DualState.PASSIVE if fc else DualState.ACTIVE3
        elif s == DualState.ACTIVE3:
            if event == E.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif event == E.INCREASE_D:
                self.state = DualState.ACTIVE2


@dataclass
class _NeighborInfo:
    report_distance: int = INF
    expect_reply: bool = False
    need_to_reply: bool = False


@dataclass
class RouteInfo:
    """Route-to-root state (Dual.h RouteInfo)."""

    distance: int = INF
    report_distance: int = INF
    feasible_distance: int = INF
    nexthop: Optional[str] = None
    sm: DualStateMachine = field(default_factory=DualStateMachine)
    neighbor_infos: Dict[str, _NeighborInfo] = field(default_factory=dict)
    cornet: List[str] = field(default_factory=list)  # pending-query stack

    def __str__(self) -> str:
        return (
            f"[{self.sm.state.value}] {self.nexthop or 'None'} "
            f"({self.distance}, {self.report_distance}, "
            f"{self.feasible_distance})"
        )


class Dual:
    """One diffusing computation toward one root (Dual.h:66)."""

    def __init__(
        self,
        node_id: str,
        root_id: str,
        local_distances: Dict[str, int],
        nexthop_cb: Optional[
            Callable[[Optional[str], Optional[str]], None]
        ] = None,
    ) -> None:
        self.node_id = node_id
        self.root_id = root_id
        self.info = RouteInfo()
        # the caller owns this table: one shared link-cost dict for every
        # root's computation; the CALLER must record cost changes in it
        # before invoking peer_up/peer_down (DualNode does exactly that)
        self.local_distances = local_distances
        self.counters: Dict[str, DualPerRootCounters] = {}
        self.nexthop_cb = nexthop_cb
        self.children_: Set[str] = set()
        if node_id == root_id:
            # I am the root: distance 0, my own nexthop
            self.info.distance = 0
            self.info.report_distance = 0
            self.info.feasible_distance = 0
            self.info.nexthop = node_id

    # -- small helpers -----------------------------------------------------

    def _counter(self, neighbor: str) -> DualPerRootCounters:
        return self.counters.setdefault(neighbor, DualPerRootCounters())

    def _ninfo(self, neighbor: str) -> _NeighborInfo:
        return self.info.neighbor_infos.setdefault(neighbor, _NeighborInfo())

    def _neighbor_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INF) != INF

    def _set_nexthop(self, new_nh: Optional[str]) -> None:
        if self.info.nexthop != new_nh:
            if self.nexthop_cb is not None:
                self.nexthop_cb(self.info.nexthop, new_nh)
            self.info.nexthop = new_nh

    def _min_distance(self) -> int:
        if self.node_id == self.root_id:
            return 0
        return min(
            (
                _add(ld, self._ninfo(n).report_distance)
                for n, ld in self.local_distances.items()
            ),
            default=INF,
        )

    def _route_affected(self) -> bool:
        """Did the latest report-distance/local-distance change move my
        distance or invalidate my current nexthop?"""
        if not self.local_distances:
            return False
        if self.info.nexthop == self.node_id:
            return False  # I am the root
        dmin = self._min_distance()
        if self.info.distance != dmin:
            return True
        if dmin == INF:
            return False
        best = {
            n
            for n, ld in self.local_distances.items()
            if _add(ld, self._ninfo(n).report_distance) == dmin
        }
        assert self.info.nexthop is not None
        return self.info.nexthop not in best

    def _meet_feasible_condition(self) -> Optional[tuple]:
        """SNC: a neighbor with report-distance < my feasible-distance that
        also attains the current minimum.  Returns (nexthop, distance)."""
        dmin = self._min_distance()
        for n, ld in self.local_distances.items():
            if ld == INF:
                continue
            rd = self._ninfo(n).report_distance
            if rd < self.info.feasible_distance and _add(ld, rd) == dmin:
                return (n, dmin)
        return None

    # -- message emission --------------------------------------------------

    def _emit(
        self,
        out: MsgBatch,
        neighbor: str,
        mtype: DualMessageType,
        distance: int,
    ) -> None:
        out.setdefault(neighbor, []).append(
            DualMessage(dst_id=self.root_id, distance=distance, type=mtype)
        )
        c = self._counter(neighbor)
        c.total_sent += 1
        if mtype == DualMessageType.UPDATE:
            c.update_sent += 1
        elif mtype == DualMessageType.QUERY:
            c.query_sent += 1
        else:
            c.reply_sent += 1

    def _flood_updates(self, out: MsgBatch) -> None:
        for n, ld in self.local_distances.items():
            if ld != INF:
                self._emit(
                    out, n, DualMessageType.UPDATE, self.info.report_distance
                )

    def _send_reply(self, out: MsgBatch) -> None:
        assert self.info.cornet, "send reply with no pending query"
        dst = self.info.cornet.pop()
        if not self._neighbor_up(dst):
            # link down on my end: if it is merely not-yet-up here, flush
            # the reply at peer-up; if truly down, the peer sees the
            # link-down event as an implicit reply
            self._ninfo(dst).need_to_reply = True
            return
        self._emit(out, dst, DualMessageType.REPLY, self.info.report_distance)

    # -- local vs diffusing computation ------------------------------------

    def _local_computation(
        self, new_nexthop: str, new_distance: int, out: MsgBatch
    ) -> None:
        rd_changed = new_distance != self.info.report_distance
        self._set_nexthop(new_nexthop)
        self.info.distance = new_distance
        self.info.report_distance = new_distance
        self.info.feasible_distance = new_distance
        if rd_changed:
            self._flood_updates(out)

    def _diffusing_computation(self, out: MsgBatch) -> bool:
        """Freeze on the current nexthop, raise distances to its route, and
        query every up neighbor.  Returns False when nobody is reachable."""
        assert self.info.nexthop is not None
        d = _add(
            self.local_distances[self.info.nexthop],
            self._ninfo(self.info.nexthop).report_distance,
        )
        self.info.distance = d
        self.info.report_distance = d
        self.info.feasible_distance = d
        any_sent = False
        for n, ld in self.local_distances.items():
            if ld == INF:
                continue
            self._emit(out, n, DualMessageType.QUERY, d)
            self._ninfo(n).expect_reply = True
            any_sent = True
        return any_sent

    def _try_local_or_diffusing(
        self, event: DualEvent, need_reply: bool, out: MsgBatch
    ) -> None:
        if not self._route_affected():
            if need_reply:
                self._send_reply(out)
            return
        fc = self._meet_feasible_condition()
        if self.info.nexthop is None:
            assert fc is not None, "invalid nexthop must meet FC"
        if fc is not None:
            self._local_computation(fc[0], fc[1], out)
            if need_reply:
                self._send_reply(out)
        else:
            if need_reply and event != DualEvent.QUERY_FROM_SUCCESSOR:
                # a non-successor asked: answer before going active
                self._send_reply(out)
            if self._diffusing_computation(out):
                self.info.sm.process_event(event, fc=False)
            if self.info.nexthop is not None and not self._neighbor_up(
                self.info.nexthop
            ):
                self._set_nexthop(None)

    # -- input events ------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int, out: MsgBatch) -> None:
        if self.info.nexthop == neighbor:
            # stale parent from a non-graceful restart: as-if peer-down.
            # feasible-distance must also lift to INF: with no successor
            # there is nothing to be feasible against, and a frozen low fd
            # could otherwise leave every neighbor infeasible (FC assert)
            self._set_nexthop(None)
            self.info.distance = INF
            self.info.feasible_distance = INF
        self._ninfo(neighbor)
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        elif self._ninfo(neighbor).expect_reply:
            # the neighbor I was waiting on came (back) up — treat the
            # reconnect as the reply itself
            self.process_reply(
                neighbor,
                DualMessage(
                    dst_id=self.root_id,
                    distance=self._ninfo(neighbor).report_distance,
                    type=DualMessageType.REPLY,
                ),
                out,
            )
        # introduce ourselves (route advertisement) to the new neighbor
        self._emit(
            out, neighbor, DualMessageType.UPDATE, self.info.report_distance
        )
        if self._ninfo(neighbor).need_to_reply:
            self._ninfo(neighbor).need_to_reply = False
            self._emit(
                out, neighbor, DualMessageType.REPLY, self.info.report_distance
            )

    def peer_down(self, neighbor: str, out: MsgBatch) -> None:
        self.counters.pop(neighbor, None)
        self.children_.discard(neighbor)
        self._ninfo(neighbor).report_distance = INF
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.INCREASE_D, False, out)
        else:
            self.info.sm.process_event(DualEvent.INCREASE_D)
            if self._ninfo(neighbor).expect_reply:
                # down == implicit reply of "unreachable"
                self.process_reply(
                    neighbor,
                    DualMessage(
                        dst_id=self.root_id,
                        distance=INF,
                        type=DualMessageType.REPLY,
                    ),
                    out,
                )

    def process_update(
        self, neighbor: str, update: DualMessage, out: MsgBatch
    ) -> None:
        c = self._counter(neighbor)
        c.update_recv += 1
        c.total_recv += 1
        self._ninfo(neighbor).report_distance = update.distance
        if neighbor not in self.local_distances:
            return  # UPDATE raced ahead of the link-up event
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        else:
            # active: track live distance, keep rd/fd frozen
            if self.info.nexthop == neighbor:
                self.info.distance = _add(
                    self.local_distances[neighbor], update.distance
                )
            self.info.sm.process_event(DualEvent.OTHERS)

    def process_query(
        self, neighbor: str, query: DualMessage, out: MsgBatch
    ) -> None:
        c = self._counter(neighbor)
        c.query_recv += 1
        c.total_recv += 1
        self._ninfo(neighbor).report_distance = query.distance
        self.info.cornet.append(neighbor)
        event = (
            DualEvent.QUERY_FROM_SUCCESSOR
            if self.info.nexthop == neighbor
            else DualEvent.OTHERS
        )
        if self.info.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(event, True, out)
        else:
            if self.info.nexthop == neighbor:
                self.info.distance = _add(
                    self.local_distances[neighbor],
                    self._ninfo(neighbor).report_distance,
                )
            self.info.sm.process_event(event)
            self._send_reply(out)

    def process_reply(
        self, neighbor: str, reply: DualMessage, out: MsgBatch
    ) -> None:
        c = self._counter(neighbor)
        c.reply_recv += 1
        c.total_recv += 1
        ninfo = self._ninfo(neighbor)
        if not ninfo.expect_reply:
            return  # link-down already consumed this diffusion; ignore
        ninfo.report_distance = reply.distance
        ninfo.expect_reply = False
        if any(i.expect_reply for i in self.info.neighbor_infos.values()):
            return
        # last reply: every dependent has re-converged; pick the optimum.
        # fc is hardwired true (matching Dual.cpp) because the fresh
        # minimum over current report-distances IS adopted below — the
        # multi-round ACTIVE0/2 re-diffusion of full DUAL is not needed
        # when the post-diffusion route is recomputed from scratch.
        self.info.sm.process_event(DualEvent.LAST_REPLY, fc=True)
        dmin, new_nh = INF, None
        for n, ld in self.local_distances.items():
            d = _add(ld, self._ninfo(n).report_distance)
            if d < dmin:
                dmin, new_nh = d, n
        rd_changed = dmin != self.info.report_distance
        self.info.distance = dmin
        self.info.report_distance = dmin
        self.info.feasible_distance = dmin
        self._set_nexthop(new_nh)
        if rd_changed:
            self._flood_updates(out)
        if self.info.cornet:
            assert len(self.info.cornet) == 1, "one diffusion per destination"
            self._send_reply(out)

    # -- SPT accessors -----------------------------------------------------

    def has_valid_route(self) -> bool:
        return self.info.nexthop is not None and self.info.distance != INF

    def add_child(self, child: str) -> None:
        self.children_.add(child)

    def remove_child(self, child: str) -> None:
        self.children_.discard(child)

    def children(self) -> Set[str]:
        return set(self.children_)

    def spt_peers(self) -> Set[str]:
        """Parent + children — the flooding neighbor set."""
        if not self.has_valid_route():
            return set()
        peers = set(self.children_)
        if self.info.nexthop != self.node_id:
            peers.add(self.info.nexthop)
        return peers

    def status_string(self) -> str:
        return f"{self.root_id}::{self.node_id}: {self.info}"


class DualNode:
    """Multi-root container: discovers roots from the messages themselves
    and runs one `Dual` per root (Dual.h:285).  Subclasses implement the
    wire (`send_dual_messages`) and react to parent changes
    (`process_nexthop_change`) — KvStore uses the latter to move itself
    between parents' child-sets."""

    def __init__(self, node_id: str, is_root: bool = False) -> None:
        self.node_id = node_id
        self.is_root = is_root
        self.duals: Dict[str, Dual] = {}
        self.local_distances: Dict[str, int] = {}
        if is_root:
            self._add_dual(node_id)

    # -- I/O plumbing (override) -------------------------------------------

    def send_dual_messages(self, neighbor: str, msgs: DualMessages) -> bool:
        raise NotImplementedError

    def process_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        raise NotImplementedError

    # -- internals ---------------------------------------------------------

    def _add_dual(self, root_id: str) -> None:
        if root_id in self.duals:
            return
        self.duals[root_id] = Dual(
            self.node_id,
            root_id,
            self.local_distances,
            nexthop_cb=lambda old, new, r=root_id: self.process_nexthop_change(
                r, old, new
            ),
        )

    def _send_batch(self, out: MsgBatch) -> None:
        for neighbor, msgs in out.items():
            if msgs:
                self.send_dual_messages(
                    neighbor, DualMessages(src_id=self.node_id, messages=msgs)
                )

    # -- input events ------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int) -> None:
        self.local_distances[neighbor] = cost
        out: MsgBatch = {}
        for dual in self.duals.values():
            dual.peer_up(neighbor, cost, out)
        self._send_batch(out)

    def peer_down(self, neighbor: str) -> None:
        self.local_distances[neighbor] = INF
        out: MsgBatch = {}
        for dual in self.duals.values():
            dual.peer_down(neighbor, out)
        self._send_batch(out)

    def process_dual_messages(self, messages: DualMessages) -> None:
        neighbor = messages.src_id
        out: MsgBatch = {}
        for msg in messages.messages:
            self._add_dual(msg.dst_id)
            dual = self.duals[msg.dst_id]
            if msg.type == DualMessageType.UPDATE:
                dual.process_update(neighbor, msg, out)
            elif msg.type == DualMessageType.QUERY:
                dual.process_query(neighbor, msg, out)
            else:
                dual.process_reply(neighbor, msg, out)
        self._send_batch(out)

    # -- SPT selection (multi-root arbitration) ----------------------------

    def get_spt_root_id(self) -> Optional[str]:
        """Smallest discovered root with a valid route wins
        (Dual.cpp:738)."""
        for root_id in sorted(self.duals):
            if self.duals[root_id].has_valid_route():
                return root_id
        return None

    def get_spt_peers(self, root_id: Optional[str]) -> Set[str]:
        if root_id is None or root_id not in self.duals:
            return set()
        return self.duals[root_id].spt_peers()

    def get_info(self, root_id: str) -> Optional[RouteInfo]:
        dual = self.duals.get(root_id)
        return dual.info if dual is not None else None

    def status_strings(self) -> Dict[str, str]:
        return {r: d.status_string() for r, d in self.duals.items()}
