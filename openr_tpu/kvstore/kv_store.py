"""KvStore — per-area replicated, eventually-consistent key-value store.

The LSDB replication layer (openr/kvstore/KvStore.h + KvStore-inl.h):
  * conflict resolution via mergeKeyValues (openr_tpu.kvstore.merge)
  * peer FSM IDLE → SYNCING → INITIALIZED with exponential backoff and
    flap counting (KvStore.thrift:291-295, KvStore.h:455-473)
  * 3-way anti-entropy full sync: hash dump → diff response →
    finalizeFullSync push-back (KvStore-inl.h:2153, 2279, 2761)
  * incremental flooding to INITIALIZED peers, excluding the sender, with
    loop prevention via publication node_ids, TTL decrement, and a
    token-bucket flood rate limit (KvStore-inl.h:2863-3150)
  * per-key TTL countdown and expiry publication (KvStore.h:488-492)
  * self-originated key persistence + TTL refresh + version guarding
    (KvStore.h:196-215)
  * initialKvStoreSynced signal once every peer of every area reaches
    INITIALIZED (§3.3 of SURVEY)
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from openr_tpu import constants as C
from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.common.utils import ExponentialBackoff
from openr_tpu.config import KvStoreConfig
from openr_tpu.kvstore.dual import DualMessages, DualNode
from openr_tpu.kvstore.merge import dump_hashes, generate_hash, merge_key_values
from openr_tpu.kvstore.transport import KvStoreTransport, KvStoreTransportError
from openr_tpu.messaging.queue import RQueue, ReplicateQueue
from openr_tpu.types import (
    InitializationEvent,
    KeyValueRequest,
    KvRequestType,
    KvStoreAreaSummary,
    KvStorePeerState,
    PeerEvent,
    PeerSpec,
    Publication,
    Value,
)


@dataclass
class KvStorePeer:
    """Peer session state (KvStore.h:330-473)."""

    node_name: str
    spec: PeerSpec
    state: KvStorePeerState = KvStorePeerState.IDLE
    backoff: ExponentialBackoff = None  # type: ignore[assignment]
    flaps: int = 0
    num_failures: int = 0
    sync_task: Optional[asyncio.Task] = None
    #: keys whose flood this peer missed while not yet INITIALIZED —
    #: flushed when the session establishes (the reference's
    #: pendingKeysDuringInitialization, KvStore.h:468: the peer's full
    #: sync snapshot was diffed BEFORE these arrived, so without this
    #: buffer the update is lost until some later full sync)
    pending_keys: Set[str] = field(default_factory=set)


@dataclass
class SelfOriginatedValue:
    """Locally-owned key we keep alive in the network (KvStore.h:196)."""

    value: Value
    keys_to_advertise: bool = True
    ttl_refresh_task: Optional[asyncio.Task] = None


class _KvStoreDualNode(DualNode):
    """DUAL glued to one KvStoreDb: PDUs ride the peer transport; parent
    changes move this node between the parents' SPT child sets (the
    flood-topo-set exchange from the reference's flood optimization)."""

    def __init__(self, db: "KvStoreDb") -> None:
        super().__init__(db.node_name, is_root=db.config.is_flood_root)
        self.db = db

    def send_dual_messages(self, neighbor: str, msgs: DualMessages) -> bool:
        self.db.actor.spawn(
            self.db._send_dual_to_peer(neighbor, msgs),
            name=f"kvstore.{self.db.area}.dual.{neighbor}",
        )
        return True

    def process_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        # unset ourselves on the old parent, set on the new; both ends keep
        # a consistent SPT so floods traverse each tree edge exactly once
        if old_nh is not None and old_nh != self.node_id:
            self.db._send_flood_topo_set(old_nh, root_id, set_child=False)
        if new_nh is not None and new_nh != self.node_id:
            self.db._send_flood_topo_set(new_nh, root_id, set_child=True)
            # re-sync with the new parent: floods we missed while the tree
            # was reforming are healed by a fresh anti-entropy exchange
            # (FloodOptimization.md: "it will synchronize with its old and
            # new parent to make sure SPT information is consistent")
            self.db.schedule_parent_resync(new_nh)


class KvStoreDb:
    """One area's store + peers (KvStoreDb, KvStore.h:36-560)."""

    def __init__(
        self,
        actor: "KvStore",
        area: str,
        node_name: str,
        config: KvStoreConfig,
    ) -> None:
        self.actor = actor
        self.area = area
        self.node_name = node_name
        self.config = config
        self.dual: Optional[_KvStoreDualNode] = None
        if config.enable_flood_optimization:
            self.dual = _KvStoreDualNode(self)
        self.key_vals: Dict[str, Value] = {}
        self.expiry: Dict[str, float] = {}  # key -> deadline (clock time)
        self.peers: Dict[str, KvStorePeer] = {}
        self.self_originated: Dict[str, SelfOriginatedValue] = {}
        self.initial_synced = False
        #: set once the first PeerEvent for this area arrives; gates the
        #: KVSTORE_SYNCED signal so an empty store can't claim sync before
        #: LinkMonitor has even told it who its peers are
        self.peer_event_received = False

    # -- counters ----------------------------------------------------------

    def _bump(self, name: str, delta: float = 1) -> None:
        self.actor.counters.bump(f"kvstore.{name}", delta)

    # -- peer management (addThriftPeers/delThriftPeers) -------------------

    def add_peers(self, peers: Dict[str, PeerSpec]) -> None:
        register = getattr(self.actor.transport, "register_peer", None)
        # sorted: registration order drives session/full-sync scheduling
        # order, which must not depend on the caller's dict construction
        # (orlint unordered-emission)
        for name, spec in sorted(peers.items()):
            if register is not None:
                register(name, spec)
            existing = self.peers.get(name)
            if existing is not None:
                # peer re-add (e.g. graceful restart): reset to IDLE for
                # a fresh full sync.  Transition BEFORE adopting the new
                # spec: leaving INITIALIZED must tear down DUAL according
                # to the capability the old session was established with
                self._set_peer_state(existing, KvStorePeerState.IDLE)
                existing.spec = spec
                existing.backoff.report_success()
            else:
                peer = KvStorePeer(
                    node_name=name,
                    spec=spec,
                    backoff=ExponentialBackoff(
                        C.KVSTORE_SYNC_INITIAL_BACKOFF_S,
                        C.KVSTORE_SYNC_MAX_BACKOFF_S,
                        self.actor.clock,
                    ),
                )
                self.peers[name] = peer
            self._schedule_peer_sync(self.peers[name])

    def del_peers(self, names: List[str]) -> None:
        unregister = getattr(self.actor.transport, "unregister_peer", None)
        for name in names:
            if unregister is not None:
                unregister(name)
            peer = self.peers.pop(name, None)
            if peer is not None and peer.sync_task is not None:
                peer.sync_task.cancel()
            if (
                peer is not None
                and self.dual is not None
                and peer.spec.supports_flood_optimization
                and peer.state == KvStorePeerState.INITIALIZED
            ):
                self.dual.peer_down(name)
        self._maybe_signal_initial_synced()

    def _set_peer_state(self, peer: KvStorePeer, state: KvStorePeerState) -> None:
        if peer.state == state:
            return
        if peer.state == KvStorePeerState.INITIALIZED:
            # leaving INITIALIZED == one flap (KvStore.thrift flaps field)
            peer.flaps += 1
            if self.dual is not None and peer.spec.supports_flood_optimization:
                self.dual.peer_down(peer.node_name)
        peer.state = state
        peer.spec.state = state
        if (
            state == KvStorePeerState.INITIALIZED
            and self.dual is not None
            and peer.spec.supports_flood_optimization
        ):
            # DUAL runs over established peer sessions only; unit link cost
            # (the flood tree minimises hops, not metric)
            self.dual.peer_up(peer.node_name, 1)
        if state == KvStorePeerState.INITIALIZED and peer.pending_keys:
            # flush floods the peer missed while syncing
            # (floodBufferedUpdates for pendingKeysDuringInitialization)
            key_vals = {
                k: self._flood_copy(self.key_vals[k])
                for k in sorted(peer.pending_keys)
                if k in self.key_vals
            }
            peer.pending_keys.clear()
            if key_vals:
                self.actor.spawn(
                    self._flood_to_peer(
                        peer,
                        Publication(
                            key_vals=key_vals,
                            area=self.area,
                            node_ids=[self.node_name],
                        ),
                    ),
                    name=f"kvstore.{self.area}.flush.{peer.node_name}",
                )
        self.actor.counters.set(
            f"kvstore.{self.area}.peer.{peer.node_name}.state", int(state)
        )

    # -- full sync (requestThriftPeerSync, KvStore-inl.h:2153) -------------

    def _schedule_peer_sync(self, peer: KvStorePeer) -> None:
        if peer.sync_task is not None and not peer.sync_task.done():
            peer.sync_task.cancel()
        peer.sync_task = self.actor.spawn(
            self._sync_peer(peer), name=f"kvstore.{self.area}.sync.{peer.node_name}"
        )

    async def _sync_peer(self, peer: KvStorePeer) -> None:
        delay = peer.backoff.time_remaining_until_retry()
        if delay > 0:
            await self.actor.clock.sleep(delay)
        # parallel-sync window: limit concurrent full syncs (2 → 32,
        # KvStore.h:550, Constants.h:160)
        while self.actor.num_active_syncs >= self.actor.parallel_sync_limit:
            await self.actor.clock.sleep(0.05)
        self._set_peer_state(peer, KvStorePeerState.SYNCING)
        self.actor.num_active_syncs += 1
        try:
            await self._full_sync_exchange(peer.node_name)
            peer.backoff.report_success()
            self._set_peer_state(peer, KvStorePeerState.INITIALIZED)
            # widen the parallel sync window on success (KvStore.h:550)
            self.actor.parallel_sync_limit = min(
                self.actor.parallel_sync_limit * 2, C.MAX_FULL_SYNC_PENDING_COUNT
            )
            self._maybe_signal_initial_synced()
        except (KvStoreTransportError, asyncio.CancelledError) as e:
            if isinstance(e, asyncio.CancelledError):
                raise
            peer.num_failures += 1
            peer.backoff.report_error()
            self._bump("thrift.num_full_sync_failure")
            self._set_peer_state(peer, KvStorePeerState.IDLE)
            self._schedule_peer_sync(peer)
        finally:
            self.actor.num_active_syncs -= 1

    async def _full_sync_exchange(self, peer_name: str) -> None:
        """The 3-way anti-entropy exchange (hash dump -> diff -> push-back)
        against one peer; raises KvStoreTransportError on failure."""
        hashes = dump_hashes(self.key_vals)
        pub = await self.actor.transport.get_key_vals_filtered_area(
            peer_name, self.area, hashes, self.node_name
        )
        self._bump("thrift.num_full_sync")
        self.merge_publication(pub, sender=peer_name)
        # 3rd leg: push back keys the responder lacks/outdated
        if pub.tobe_updated_keys:
            back = {
                k: self._flood_copy(self.key_vals[k])
                for k in pub.tobe_updated_keys
                if k in self.key_vals
            }
            if back:
                await self.actor.transport.set_key_vals(
                    peer_name,
                    self.area,
                    Publication(
                        key_vals=back,
                        area=self.area,
                        node_ids=[self.node_name],
                    ),
                    self.node_name,
                )
                self._bump("thrift.num_finalized_sync")

    def schedule_parent_resync(self, parent: str) -> None:
        """Anti-entropy with a new SPT parent, without disturbing the peer
        FSM (the session is already INITIALIZED — only the data may have
        diverged while floods bypassed us during tree reformation)."""

        async def _resync() -> None:
            if parent not in self.peers:
                return
            try:
                await self._full_sync_exchange(parent)
                self._bump("dual.num_parent_resync")
            except KvStoreTransportError:
                self._bump("dual.num_parent_resync_failure")

        self.actor.spawn(
            _resync(), name=f"kvstore.{self.area}.parent_resync.{parent}"
        )

    def _maybe_signal_initial_synced(self, grace_expired: bool = False) -> None:
        """Signal only after LinkMonitor told us our peers (first PeerEvent)
        — or after the link-discovery grace window for standalone stores
        (Constants.h:27 kMaxDurationLinkDiscovery)."""
        if self.initial_synced:
            return
        if not (self.peer_event_received or grace_expired):
            return
        if all(
            p.state == KvStorePeerState.INITIALIZED for p in self.peers.values()
        ):
            self.initial_synced = True
            self.actor.on_area_synced(self.area)

    # -- responder side ----------------------------------------------------

    def handle_full_sync_request(
        self, key_val_hashes: Dict[str, Tuple[int, str, Optional[int]]], sender: str
    ) -> Publication:
        """Diff the initiator's digests against our store
        (dumpDifference semantics): return values we have newer/missing,
        and name keys where the initiator is ahead (tobeUpdatedKeys)."""
        newer: Dict[str, Value] = {}
        tobe_updated: List[str] = []
        for key, value in self.key_vals.items():
            theirs = key_val_hashes.get(key)
            if theirs is None:
                newer[key] = self._flood_copy(value)
                continue
            their_version, their_originator, their_hash = theirs
            ours = (value.version, value.originator_id, value.hash)
            if ours == (their_version, their_originator, their_hash):
                continue
            mine_key = (value.version, value.originator_id)
            their_key = (their_version, their_originator)
            if mine_key > their_key:
                newer[key] = self._flood_copy(value)
            elif mine_key < their_key:
                tobe_updated.append(key)
            else:
                # same (version, originator) but different hash: the
                # digest can't order the values, so send ours AND name
                # the key tobe-updated — compareValues on each side
                # settles the winner (without the push-back, an initiator
                # whose value wins the larger-value tie-break keeps it
                # while we never learn it: permanent divergence)
                newer[key] = self._flood_copy(value)
                tobe_updated.append(key)
        for key in key_val_hashes:
            if key not in self.key_vals:
                tobe_updated.append(key)
        return Publication(
            key_vals=newer,
            tobe_updated_keys=sorted(tobe_updated),
            area=self.area,
            node_ids=[self.node_name],
        )

    # -- merge + flood (KvStore-inl.h:2863-3150) ---------------------------

    def _flood_copy(self, value: Value) -> Value:
        """Copy with TTL decremented (Constants.h kTtlDecrement) so looping
        values eventually die."""
        ttl = value.ttl
        if ttl != C.TTL_INFINITY:
            ttl = ttl - C.TTL_DECREMENT_MS
        return Value(
            version=value.version,
            originator_id=value.originator_id,
            value=value.value,
            ttl=ttl,
            ttl_version=value.ttl_version,
            hash=value.hash,
        )

    def merge_publication(
        self, pub: Publication, sender: Optional[str] = None
    ) -> Dict[str, Value]:
        """Merge a peer publication; publishes + floods accepted updates.
        Returns the accepted delta."""
        # loop prevention (mergePublication: drop if our id already in path)
        if pub.node_ids is not None and self.node_name in pub.node_ids:
            self._bump("looped_publications")
            return {}
        result = merge_key_values(self.key_vals, pub.key_vals, sender=sender)
        if result.inconsistency_detected_with_originator and sender in self.peers:
            # force the peer back through full sync (peer → IDLE)
            peer = self.peers[sender]
            self._set_peer_state(peer, KvStorePeerState.IDLE)
            self._schedule_peer_sync(peer)
        self._refresh_expiries(result.key_vals)
        self._guard_self_originated(result.key_vals)
        if result.key_vals:
            self._bump("received_key_vals", len(result.key_vals))
            ctx = pub.trace_ctx
            tracer = self.actor.tracer
            if tracer.enabled:
                # key arrival: continue the flood's trace when the
                # publication carries one, else mint here — a remote
                # arrival is itself an event origin (full-sync deltas,
                # untraced senders)
                if ctx is None:
                    ctx = tracer.start_trace(
                        "kvstore.key_arrival",
                        module="kvstore",
                        area=self.area,
                        sender=sender or "",
                        keys=len(result.key_vals),
                    )
                else:
                    span = tracer.instant(
                        "kvstore.key_arrival",
                        ctx,
                        module="kvstore",
                        area=self.area,
                        sender=sender or "",
                        keys=len(result.key_vals),
                    )
                    ctx = tracer.child_ctx(span, ctx)
            self.publish(
                Publication(
                    key_vals=dict(result.key_vals),
                    area=self.area,
                    node_ids=list(pub.node_ids or []),
                    trace_ctx=ctx,
                ),
                sender=sender,
            )
        return result.key_vals

    def publish(self, pub: Publication, sender: Optional[str] = None) -> None:
        """Push to local subscribers and flood to peers."""
        self.actor.publications_queue.push(pub)
        self._flood(pub, sender)

    def _flood(self, pub: Publication, sender: Optional[str]) -> None:
        node_ids = list(pub.node_ids or [])
        if self.node_name not in node_ids:
            node_ids.append(self.node_name)
        flood_pub = Publication(
            key_vals={k: self._flood_copy(v) for k, v in pub.key_vals.items()},
            expired_keys=list(pub.expired_keys),
            area=self.area,
            node_ids=node_ids,
            # flooding metadata: the trace context travels with the
            # publication hop by hop so every receiving store/Decision
            # joins the originating event's trace
            trace_ctx=pub.trace_ctx,
        )
        if not flood_pub.key_vals and not flood_pub.expired_keys:
            return
        flood_set = self._flood_peers()
        # sorted: flood fan-out order is the emission order every peer's
        # arrival sequence (and the SimClock event schedule) inherits —
        # name-derived, not session-table order (orlint unordered-emission)
        for name, peer in sorted(self.peers.items()):
            if name == sender:
                continue  # dedup: never reflect to the sender
            if peer.state != KvStorePeerState.INITIALIZED:
                # buffer for flush at session establishment — this
                # peer's in-flight full sync snapshot predates these keys
                peer.pending_keys.update(flood_pub.key_vals.keys())
                continue
            if flood_set is not None and name not in flood_set:
                continue  # flood optimization: SPT edges only
            if name in (pub.node_ids or []):
                continue  # path already visited this node
            self.actor.spawn(
                self._flood_to_peer(peer, flood_pub),
                name=f"kvstore.{self.area}.flood.{name}",
            )

    def _flood_peers(self) -> Optional[Set[str]]:
        """SPT parent+children when flood optimization has a converged
        tree; None = flood to everyone (getFloodPeers semantics).  Peers
        that never advertised DUAL support stay on full flooding so a
        mixed-capability network doesn't partition."""
        if self.dual is None:
            return None
        root = self.dual.get_spt_root_id()
        if root is None:
            return None  # no converged SPT yet: fall back to full flood
        peers = self.dual.get_spt_peers(root)
        peers.update(
            name
            for name, p in self.peers.items()
            if not p.spec.supports_flood_optimization
        )
        return peers

    # -- DUAL plumbing (flood optimization) --------------------------------

    async def _send_dual_to_peer(self, name: str, msgs: DualMessages) -> None:
        try:
            await self.actor.transport.send_dual_messages(
                name, self.area, msgs, self.node_name
            )
            self._bump("dual.num_pkt_sent")
        except KvStoreTransportError:
            # peer unreachable: its session teardown will fire peer_down
            self._bump("dual.num_pkt_send_failure")

    def _send_flood_topo_set(
        self, parent: str, root_id: str, set_child: bool
    ) -> None:
        async def _send() -> None:
            try:
                await self.actor.transport.set_flood_topo_child(
                    parent, self.area, root_id, self.node_name,
                    set_child, self.node_name,
                )
            except KvStoreTransportError:
                self._bump("dual.num_flood_topo_set_failure")

        self.actor.spawn(
            _send(), name=f"kvstore.{self.area}.floodtopo.{parent}"
        )

    async def _flood_to_peer(self, peer: KvStorePeer, pub: Publication) -> None:
        # flood rate limit (config flood_rate, KvStore-inl.h rate limiter)
        await self.actor.flood_limiter.acquire()
        try:
            await self.actor.transport.set_key_vals(
                peer.node_name, self.area, pub, self.node_name
            )
            self._bump("thrift.num_flood_pub")
        except KvStoreTransportError:
            peer.num_failures += 1
            self._bump("thrift.num_flood_key_vals_failure")
            # flooding failures degrade the peer: force re-sync
            self._set_peer_state(peer, KvStorePeerState.IDLE)
            self._schedule_peer_sync(peer)

    # -- TTL management (KvStore.h:488-492, -inl.h:2707) -------------------

    def _refresh_expiries(self, key_vals: Dict[str, Value]) -> None:
        now = self.actor.clock.now()
        for key, value in key_vals.items():
            if value.ttl == C.TTL_INFINITY:
                self.expiry.pop(key, None)
            else:
                self.expiry[key] = now + value.ttl / 1000.0

    def expire_keys(self) -> None:
        """Drop keys whose TTL lapsed; publish expirations."""
        now = self.actor.clock.now()
        expired = [k for k, dl in self.expiry.items() if dl <= now]
        if not expired:
            return
        for k in expired:
            self.expiry.pop(k, None)
            self.key_vals.pop(k, None)
        self._bump("expired_keys", len(expired))
        self.actor.publications_queue.push(
            Publication(expired_keys=sorted(expired), area=self.area)
        )

    def next_expiry(self) -> Optional[float]:
        return min(self.expiry.values()) if self.expiry else None

    # -- self-originated keys (KvStore.h:196-215) --------------------------

    def persist_self_originated_key(
        self, key: str, data: bytes, trace_ctx=None
    ) -> Value:
        """Advertise and keep alive a locally-owned key; version guards
        against overrides from the network."""
        existing_store = self.key_vals.get(key)
        existing_self = self.self_originated.get(key)
        version = 1
        if existing_self is not None:
            if (
                existing_self.value.value == data
                and existing_store is not None
                and existing_store.version == existing_self.value.version
                and existing_store.originator_id == self.node_name
            ):
                # unchanged data still owned by us in the store: no-op
                # (periodic re-persists must not churn versions network-wide)
                return existing_self.value
            version = existing_self.value.version + 1
        elif existing_store is not None:
            version = existing_store.version + 1
        value = Value(
            version=version,
            originator_id=self.node_name,
            value=data,
            ttl=self.config.self_originated_key_ttl_ms,
            ttl_version=self._ttl_clock(),
        )
        value.hash = generate_hash(value)
        sov = SelfOriginatedValue(value=value)
        old = self.self_originated.get(key)
        if old is not None and old.ttl_refresh_task is not None:
            old.ttl_refresh_task.cancel()
        self.self_originated[key] = sov
        sov.ttl_refresh_task = self.actor.spawn(
            self._ttl_refresh_loop(key), name=f"kvstore.{self.area}.ttl.{key}"
        )
        self._apply_local(key, value, trace_ctx)
        return value

    def set_self_originated_key(self, key: str, data: bytes, version: int) -> None:
        """One-shot advertise (setKey): no persistence/refresh."""
        if version == 0:
            existing = self.key_vals.get(key)
            version = (existing.version + 1) if existing is not None else 1
        value = Value(
            version=version,
            originator_id=self.node_name,
            value=data,
            ttl=self.config.self_originated_key_ttl_ms,
            ttl_version=self._ttl_clock(),
        )
        value.hash = generate_hash(value)
        self._apply_local(key, value)

    def _ttl_clock(self) -> int:
        """Incarnation-monotone ttl_version seed: the refresh-interval
        count since the epoch of the injected clock.  A restarted
        node's ttl clock must EXCEED its previous incarnation's — the
        fleet's copies carry the old incarnation's ttl_version, the
        3-way sync's hash digest (version, originator, hash) cannot see
        the divergence, and refreshes with a lower ttl_version are
        dropped as stale until the fleet's copies silently age out one
        TTL after the restart.  Seeding from time (the previous
        incarnation advanced its clock at the same 1-per-interval rate
        it was alive) keeps the fresh clock ahead without any protocol
        change; `_guard_self_originated`'s fast-forward stays as the
        belt for restarts inside a single interval tick."""
        interval_ms = max(self.config.self_originated_key_ttl_ms / 4, 1)
        return int(self.actor.clock.now_ms() // interval_ms) + 1

    def erase_self_originated_key(self, key: str) -> None:
        """Stop refreshing; the network expires the key naturally
        (eraseKey semantics)."""
        sov = self.self_originated.pop(key, None)
        if sov is not None and sov.ttl_refresh_task is not None:
            sov.ttl_refresh_task.cancel()

    def _apply_local(self, key: str, value: Value, trace_ctx=None) -> None:
        merged = merge_key_values(self.key_vals, {key: value})
        self._refresh_expiries(merged.key_vals)
        if merged.key_vals:
            tracer = self.actor.tracer
            if trace_ctx is not None and tracer.enabled:
                span = tracer.instant(
                    "kvstore.key_advertise",
                    trace_ctx,
                    module="kvstore",
                    area=self.area,
                    key=key,
                )
                trace_ctx = tracer.child_ctx(span, trace_ctx)
            self.publish(
                Publication(
                    key_vals=dict(merged.key_vals),
                    area=self.area,
                    node_ids=[],
                    trace_ctx=trace_ctx,
                )
            )

    def _guard_self_originated(self, accepted: Dict[str, Value]) -> None:
        """If the network overrode one of our self-originated keys, bump our
        version above the interloper and re-advertise.

        The override has two faces: an INTERLOPER (another originator
        claiming our key) and our own PREVIOUS INCARNATION — after a
        restart we re-originate at version 1 while the network still
        remembers the old incarnation's higher version.  Without
        re-origination the fossil wins every merge, our TTL refreshes
        are rejected as stale, nobody else refreshes the fossil either,
        and the key starves fleet-wide one TTL after the restart — a
        rolling upgrade would silently withdraw every bounced node's
        prefixes ~5 minutes later.  Both cases adopt a version above
        the override and re-advertise our CURRENT data (the reference's
        checkSelfAdjustKey semantics)."""
        # sorted: re-origination order is re-advertise (flood) order —
        # keep it content-derived, not arrival-derived (orlint
        # unordered-emission)
        for key, value in sorted(accepted.items()):
            sov = self.self_originated.get(key)
            if sov is None:
                continue
            if value.originator_id == self.node_name:
                ours = sov.value
                if value.value is None:
                    continue  # ttl-only refresh, not an override
                if value.version == ours.version and value.hash == ours.hash:
                    # the same advertisement — but a restarted node's
                    # TTL-VERSION clock starts over at 0 while the
                    # fleet's copies carry the previous incarnation's
                    # higher ttl_version, so every refresh we send is
                    # rejected as stale until the fleet's copies age
                    # out (one TTL after the bounce).  Fast-forward our
                    # clock past the fossil's so the next refresh is
                    # accepted everywhere.
                    if value.ttl_version > ours.ttl_version:
                        ours.ttl_version = value.ttl_version
                        self._bump("self_originated_ttl_fastforward")
                    continue
                if value.version < ours.version:
                    continue  # our own advertisement echoing back
                self._bump("self_originated_incarnation_guard")
            else:
                self._bump("self_originated_key_guard")
            new_value = Value(
                version=value.version + 1,
                originator_id=self.node_name,
                value=sov.value.value,
                ttl=sov.value.ttl,
                ttl_version=0,
            )
            new_value.hash = generate_hash(new_value)
            sov.value = new_value
            self._apply_local(key, new_value)

    async def _ttl_refresh_loop(self, key: str) -> None:
        """Bump ttlVersion at 1/4 of the TTL interval
        (advertiseTtlUpdates)."""
        interval = max(self.config.self_originated_key_ttl_ms / 4000.0, 0.05)
        while True:
            await self.actor.clock.sleep(interval)
            sov = self.self_originated.get(key)
            if sov is None:
                return
            sov.value.ttl_version += 1
            ttl_update = Value(
                version=sov.value.version,
                originator_id=self.node_name,
                value=None,  # ttl-only update
                ttl=sov.value.ttl,
                ttl_version=sov.value.ttl_version,
            )
            merged = merge_key_values(self.key_vals, {key: ttl_update})
            self._refresh_expiries(merged.key_vals)
            if merged.key_vals:
                self.publish(
                    Publication(
                        key_vals=dict(merged.key_vals),
                        area=self.area,
                        node_ids=[],
                    )
                )

    # -- dumps -------------------------------------------------------------

    def get_key_vals(self, keys: List[str]) -> Dict[str, Value]:
        return {k: self.key_vals[k] for k in keys if k in self.key_vals}

    def dump_all(self, prefix: str = "") -> Dict[str, Value]:
        return {
            k: v for k, v in self.key_vals.items() if k.startswith(prefix)
        }

    def summary(self) -> KvStoreAreaSummary:
        return KvStoreAreaSummary(
            area=self.area,
            peers_map={n: p.spec for n, p in self.peers.items()},
            key_vals_count=len(self.key_vals),
            key_vals_bytes=sum(
                len(v.value or b"") for v in self.key_vals.values()
            ),
        )


class _RateLimiter:
    """Token bucket on the shared clock; no-op when rate == 0."""

    def __init__(self, clock: Clock, rate: float, burst: int) -> None:
        self.clock = clock
        self.rate = rate
        self.burst = max(burst, 1)
        self.tokens = float(self.burst)
        self.last = clock.now()

    async def acquire(self) -> None:
        if self.rate <= 0:
            return
        while True:
            now = self.clock.now()
            self.tokens = min(
                self.burst, self.tokens + (now - self.last) * self.rate
            )
            self.last = now
            if self.tokens >= 1:
                self.tokens -= 1
                return
            await self.clock.sleep((1 - self.tokens) / self.rate)


class KvStore(Actor):
    """The KvStore module: areas, queue plumbing, RPC dispatch
    (openr/kvstore/KvStore.h:575-835)."""

    def __init__(
        self,
        node_name: str,
        clock: Clock,
        config: KvStoreConfig,
        areas: List[str],
        transport: KvStoreTransport,
        publications_queue: ReplicateQueue,
        peer_updates_reader: Optional[RQueue] = None,
        kv_request_reader: Optional[RQueue] = None,
        initialization_cb: Optional[Callable[[InitializationEvent], None]] = None,
        counters: Optional[CounterMap] = None,
        tracer=None,
    ) -> None:
        super().__init__("kvstore", clock, counters)
        from openr_tpu.tracing import disabled_tracer

        self.tracer = tracer if tracer is not None else disabled_tracer()
        self.node_name = node_name
        self.config = config
        self.transport = transport
        self.publications_queue = publications_queue
        self.peer_updates_reader = peer_updates_reader
        self.kv_request_reader = kv_request_reader
        self.initialization_cb = initialization_cb
        self.num_active_syncs = 0
        self.parallel_sync_limit = C.PARALLEL_SYNC_LIMIT_INITIAL
        self.flood_limiter = _RateLimiter(
            clock, config.flood_rate_msgs_per_sec, config.flood_rate_burst_size
        )
        self.areas: Dict[str, KvStoreDb] = {
            a: KvStoreDb(self, a, node_name, config) for a in areas
        }
        self._synced_areas: Set[str] = set()
        self._initial_sync_signaled = False

    # -- module lifecycle --------------------------------------------------

    def start(self) -> None:
        if self.peer_updates_reader is not None:
            self.spawn_queue_loop(
                self.peer_updates_reader, self._on_peer_event, "kvstore.peers"
            )
        if self.kv_request_reader is not None:
            self.spawn_queue_loop(
                self.kv_request_reader, self._on_kv_request, "kvstore.requests"
            )
        self.spawn(self._ttl_expiry_loop(), name="kvstore.ttl")
        # standalone/leaf fallback: if no peer event ever arrives, declare
        # sync after the link-discovery bound rather than hanging forever
        self.schedule(C.MAX_DURATION_LINK_DISCOVERY_S, self._grace_sync_check)

    def _grace_sync_check(self) -> None:
        for db in self.areas.values():
            db._maybe_signal_initial_synced(grace_expired=True)

    async def _ttl_expiry_loop(self) -> None:
        while True:
            deadlines = [
                db.next_expiry() for db in self.areas.values() if db.next_expiry()
            ]
            now = self.clock.now()
            sleep_for = min(
                [max(dl - now, 0.0) for dl in deadlines], default=0.5
            )
            await self.clock.sleep(min(sleep_for, 0.5))
            for db in self.areas.values():
                db.expire_keys()

    # -- queue handlers ----------------------------------------------------

    def _on_peer_event(self, event: PeerEvent) -> None:
        db = self.areas.get(event.area)
        if db is None:
            return
        db.peer_event_received = True
        if event.peers_to_add:
            db.add_peers(event.peers_to_add)
        if event.peers_to_del:
            db.del_peers(event.peers_to_del)
        db._maybe_signal_initial_synced()

    def _on_kv_request(self, req: KeyValueRequest) -> None:
        db = self.areas.get(req.area)
        if db is None:
            return
        if req.request_type == KvRequestType.PERSIST_KEY:
            db.persist_self_originated_key(req.key, req.value, req.trace_ctx)
        elif req.request_type == KvRequestType.SET_KEY:
            db.set_self_originated_key(req.key, req.value, req.version or 0)
        elif req.request_type == KvRequestType.CLEAR_KEY:
            db.erase_self_originated_key(req.key)

    # -- transport-facing handlers (responder side) ------------------------

    async def handle_full_sync_request(
        self, area: str, key_val_hashes, sender: str
    ) -> Publication:
        db = self.areas.get(area)
        if db is None:
            raise KvStoreTransportError(f"unknown area {area}")
        return db.handle_full_sync_request(key_val_hashes, sender)

    async def handle_set_key_vals(
        self, area: str, publication: Publication, sender: str
    ) -> None:
        db = self.areas.get(area)
        if db is None:
            raise KvStoreTransportError(f"unknown area {area}")
        db.merge_publication(publication, sender=sender)

    async def handle_dual_messages(self, area: str, messages) -> None:
        db = self.areas.get(area)
        if db is None or db.dual is None:
            raise KvStoreTransportError(f"no dual in area {area}")
        db.dual.process_dual_messages(messages)
        self.counters.bump("kvstore.dual.num_pkt_recv")

    async def handle_flood_topo_set(
        self, area: str, root_id: str, child: str, set_child: bool
    ) -> None:
        db = self.areas.get(area)
        if db is None or db.dual is None:
            raise KvStoreTransportError(f"no dual in area {area}")
        dual = db.dual.duals.get(root_id)
        if dual is None:
            return
        if set_child:
            dual.add_child(child)
        else:
            dual.remove_child(child)

    # -- public API (ctrl surface) -----------------------------------------

    def set_key_vals(self, area: str, key_vals: Dict[str, Value]) -> None:
        """API ingress (thrift setKvStoreKeyVals): merge + flood."""
        db = self.areas[area]
        db.merge_publication(Publication(key_vals=key_vals, area=area))

    def get_key_vals(self, area: str, keys: List[str]) -> Dict[str, Value]:
        return self.areas[area].get_key_vals(keys)

    def dump_all(self, area: str, prefix: str = "") -> Dict[str, Value]:
        return self.areas[area].dump_all(prefix)

    def summaries(self) -> Dict[str, KvStoreAreaSummary]:
        return {a: db.summary() for a, db in self.areas.items()}

    # -- fleet-liveness heartbeat key family (openr_tpu.fleet.liveness) ----

    def advertise_fleet_heartbeat(self, area: str, incarnation: int) -> Value:
        """Advertise this daemon's ``fleet:member:<name>`` liveness key:
        a TTL-bearing self-originated key whose payload carries the
        incarnation stamp (the PR-12 ``node.start_ms`` discipline).  The
        existing self-originated TTL refresh loop IS the heartbeat — an
        unchanged incarnation re-persist is a version no-op network-wide,
        and key expiry is exactly the liveness tracker's death signal."""
        import json as _json

        from openr_tpu.types import fleet_member_key

        payload = _json.dumps(
            {"incarnation": int(incarnation), "node": self.node_name},
            sort_keys=True,
        ).encode()
        self.counters.bump("kvstore.fleet_heartbeat_advertised")
        return self.areas[area].persist_self_originated_key(
            fleet_member_key(self.node_name), payload
        )

    def fleet_member_heartbeats(self, area: str) -> Dict[str, dict]:
        """The fleet-liveness read surface: every unexpired
        ``fleet:member:*`` key in the area, parsed to
        ``{node: {incarnation, version, ttl_version, originator}}``."""
        import json as _json

        from openr_tpu.types import (
            FLEET_MEMBER_MARKER,
            parse_fleet_member_key,
        )

        out: Dict[str, dict] = {}
        for key, value in self.areas[area].dump_all(
            FLEET_MEMBER_MARKER
        ).items():
            node = parse_fleet_member_key(key)
            if node is None or value.value is None:
                continue
            try:
                body = _json.loads(value.value.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            out[node] = {
                "incarnation": int(body.get("incarnation", 0)),
                "version": value.version,
                "ttl_version": value.ttl_version,
                "originator": value.originator_id,
            }
        return out

    def peer_state(self, area: str, peer: str) -> Optional[KvStorePeerState]:
        p = self.areas[area].peers.get(peer)
        return p.state if p is not None else None

    def request_full_sync(self, area: Optional[str] = None) -> int:
        """Force every peer session (one area, or all) back through the
        3-way anti-entropy full sync — the cold-boot / graceful-restart
        recovery path: a supervisor restarting this daemon calls it so the
        fresh store reconverges even for peers whose sessions were re-added
        before the restart completed.  Backoffs are cleared (this is an
        operator/supervisor request, not a failure).  Returns the number of
        peers scheduled."""
        n = 0
        # sorted (areas, then peer names): full-sync scheduling order is
        # an emission order — a restarted node must reconverge along the
        # same sequence every replay (orlint unordered-emission)
        for a, db in sorted(self.areas.items()):
            if area is not None and a != area:
                continue
            for _pname, peer in sorted(db.peers.items()):
                db._set_peer_state(peer, KvStorePeerState.IDLE)
                peer.backoff.report_success()
                db._schedule_peer_sync(peer)
                n += 1
        self.counters.bump("kvstore.full_sync_requests")
        return n

    def get_flood_topo(self, area: str) -> Optional[Dict[str, dict]]:
        """SPT summary per discovered root (getKvStoreFloodTopoArea /
        SptInfos semantics): nexthop, distance, children, chosen root.
        None = flood optimization disabled; {} = enabled, no root
        discovered yet."""
        db = self.areas[area]
        if db.dual is None:
            return None
        chosen = db.dual.get_spt_root_id()
        out: Dict[str, dict] = {}
        for root_id, dual in db.dual.duals.items():
            out[root_id] = {
                "passive": dual.info.sm.state.value == "PASSIVE",
                "nexthop": dual.info.nexthop,
                "distance": dual.info.distance,
                "children": sorted(dual.children()),
                "is_chosen": root_id == chosen,
            }
        return out

    # -- initialization sequencing ----------------------------------------

    def on_area_synced(self, area: str) -> None:
        self._synced_areas.add(area)
        if self._initial_sync_signaled:
            return
        if self._synced_areas >= set(self.areas):
            self._initial_sync_signaled = True
            self.counters.bump("kvstore.initial_sync_complete")
            if self.initialization_cb is not None:
                self.initialization_cb(InitializationEvent.KVSTORE_SYNCED)
