"""KvStore peer transport — the RPC plane between stores.

The reference talks fbthrift over TCP (KvStore.h:460-466 templated client).
Here the peer API is an abstract transport so the same KvStore runs over:
  * `InProcessTransport` — N stores in one process with simulated latency
    and failure injection (the KvStoreTestFixture/OpenrWrapper pattern,
    multi-store tests in kvstore/tests/KvStoreTest.cpp run real thrift in
    one process; ours runs in virtual time)
  * a real socket transport (openr_tpu.ctrl) for multi-host deployment
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Set, Tuple

from openr_tpu.common.runtime import Clock
from openr_tpu.types import Publication


class KvStoreTransportError(RuntimeError):
    pass


class KvStoreTransport:
    """Async peer API (mirrors the thrift KvStore service surface)."""

    async def get_key_vals_filtered_area(
        self,
        peer_node: str,
        area: str,
        key_val_hashes: Dict[str, Tuple[int, str, Optional[int]]],
        sender_id: str,
    ) -> Publication:
        """Full-sync request: send (version, originatorId, hash) digests;
        responder returns newer values + tobe_updated_keys."""
        raise NotImplementedError

    async def set_key_vals(
        self, peer_node: str, area: str, publication: Publication, sender_id: str
    ) -> None:
        """Flood/finalize: push key-vals into the peer's store."""
        raise NotImplementedError

    async def send_dual_messages(
        self, peer_node: str, area: str, messages, sender_id: str
    ) -> None:
        """Deliver DUAL flood-topology PDUs to a peer (if/Dual.thrift)."""
        raise NotImplementedError

    async def set_flood_topo_child(
        self,
        peer_node: str,
        area: str,
        root_id: str,
        child: str,
        set_child: bool,
        sender_id: str,
    ) -> None:
        """FloodTopoSet: (un)register `child` in the peer's SPT child set
        for `root_id` (KvStore floodTopoSetParams semantics)."""
        raise NotImplementedError


class InProcessTransport(KvStoreTransport):
    """Registry-based transport for in-process multi-store emulation.

    Latency is served from the shared clock (virtual in tests).  Failure
    injection mirrors `semifuture_injectThriftFailure` (KvStore.h:92):
    `fail(a, b)` makes calls a→b raise until `heal(a, b)`.
    """

    def __init__(self, clock: Clock, latency_s: float = 0.0) -> None:
        self.clock = clock
        self.latency_s = latency_s
        self._stores: Dict[str, object] = {}  # node -> KvStore actor
        self._failed: Set[Tuple[str, str]] = set()
        self.num_calls = 0

    def register(self, node: str, store) -> None:
        self._stores[node] = store

    def unregister(self, node: str) -> None:
        self._stores.pop(node, None)

    def fail(self, src: str, dst: str) -> None:
        self._failed.add((src, dst))

    def heal(self, src: str, dst: str) -> None:
        self._failed.discard((src, dst))

    async def _call(self, src: str, dst: str, fn: Callable):
        self.num_calls += 1
        if self.latency_s:
            await self.clock.sleep(self.latency_s)
        if (src, dst) in self._failed or dst not in self._stores:
            raise KvStoreTransportError(f"{src} -> {dst} unreachable")
        return await fn(self._stores[dst])

    async def get_key_vals_filtered_area(
        self, peer_node, area, key_val_hashes, sender_id
    ) -> Publication:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_full_sync_request(
                area, key_val_hashes, sender_id
            ),
        )

    async def set_key_vals(self, peer_node, area, publication, sender_id) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_set_key_vals(area, publication, sender_id),
        )

    async def send_dual_messages(
        self, peer_node, area, messages, sender_id
    ) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_dual_messages(area, messages),
        )

    async def set_flood_topo_child(
        self, peer_node, area, root_id, child, set_child, sender_id
    ) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_flood_topo_set(
                area, root_id, child, set_child
            ),
        )
