"""KvStore peer transport — the RPC plane between stores.

The reference talks fbthrift over TCP (KvStore.h:460-466 templated client).
Here the peer API is an abstract transport so the same KvStore runs over:
  * `InProcessTransport` — N stores in one process with simulated latency
    and failure injection (the KvStoreTestFixture/OpenrWrapper pattern,
    multi-store tests in kvstore/tests/KvStoreTest.cpp run real thrift in
    one process; ours runs in virtual time)
  * a real socket transport (openr_tpu.ctrl) for multi-host deployment
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Set, Tuple

from openr_tpu.common.runtime import Clock
from openr_tpu.types import PeerSpec, Publication


class KvStoreTransportError(RuntimeError):
    pass


class KvStoreTransport:
    """Async peer API (mirrors the thrift KvStore service surface)."""

    async def get_key_vals_filtered_area(
        self,
        peer_node: str,
        area: str,
        key_val_hashes: Dict[str, Tuple[int, str, Optional[int]]],
        sender_id: str,
    ) -> Publication:
        """Full-sync request: send (version, originatorId, hash) digests;
        responder returns newer values + tobe_updated_keys."""
        raise NotImplementedError

    async def set_key_vals(
        self, peer_node: str, area: str, publication: Publication, sender_id: str
    ) -> None:
        """Flood/finalize: push key-vals into the peer's store."""
        raise NotImplementedError

    async def send_dual_messages(
        self, peer_node: str, area: str, messages, sender_id: str
    ) -> None:
        """Deliver DUAL flood-topology PDUs to a peer (if/Dual.thrift)."""
        raise NotImplementedError

    async def set_flood_topo_child(
        self,
        peer_node: str,
        area: str,
        root_id: str,
        child: str,
        set_child: bool,
        sender_id: str,
    ) -> None:
        """FloodTopoSet: (un)register `child` in the peer's SPT child set
        for `root_id` (KvStore floodTopoSetParams semantics)."""
        raise NotImplementedError


class InProcessTransport(KvStoreTransport):
    """Registry-based transport for in-process multi-store emulation.

    Latency is served from the shared clock (virtual in tests).  Failure
    injection mirrors `semifuture_injectThriftFailure` (KvStore.h:92):
    `fail(a, b)` makes calls a→b raise until `heal(a, b)`.
    """

    def __init__(self, clock: Clock, latency_s: float = 0.0) -> None:
        self.clock = clock
        self.latency_s = latency_s
        self._stores: Dict[str, object] = {}  # node -> KvStore actor
        self._failed: Set[Tuple[str, str]] = set()
        #: (src, dst) -> additional directional latency (chaos injection)
        self._extra_latency: Dict[Tuple[str, str], float] = {}
        self.num_calls = 0
        self.num_failed_calls = 0

    def register(self, node: str, store) -> None:
        self._stores[node] = store

    def unregister(self, node: str) -> None:
        self._stores.pop(node, None)

    def fail(self, src: str, dst: str) -> None:
        self._failed.add((src, dst))

    def heal(self, src: str, dst: str) -> None:
        self._failed.discard((src, dst))

    def set_latency(self, src: str, dst: str, extra_s: float) -> None:
        """Add directional src->dst RPC latency on top of the base
        (chaos kv_rpc_latency; 0 clears)."""
        if extra_s <= 0:
            self._extra_latency.pop((src, dst), None)
        else:
            self._extra_latency[(src, dst)] = extra_s

    async def _call(self, src: str, dst: str, fn: Callable):
        self.num_calls += 1
        latency = self.latency_s + self._extra_latency.get((src, dst), 0.0)
        if latency:
            await self.clock.sleep(latency)
        if (src, dst) in self._failed or dst not in self._stores:
            self.num_failed_calls += 1
            raise KvStoreTransportError(f"{src} -> {dst} unreachable")
        return await fn(self._stores[dst])

    async def get_key_vals_filtered_area(
        self, peer_node, area, key_val_hashes, sender_id
    ) -> Publication:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_full_sync_request(
                area, key_val_hashes, sender_id
            ),
        )

    async def set_key_vals(self, peer_node, area, publication, sender_id) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_set_key_vals(area, publication, sender_id),
        )

    async def send_dual_messages(
        self, peer_node, area, messages, sender_id
    ) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_dual_messages(area, messages),
        )

    async def set_flood_topo_child(
        self, peer_node, area, root_id, child, set_child, sender_id
    ) -> None:
        return await self._call(
            sender_id,
            peer_node,
            lambda store: store.handle_flood_topo_set(
                area, root_id, child, set_child
            ),
        )


class TcpKvStoreTransport(KvStoreTransport):
    """Real peer transport: each call is an RPC to the peer's ctrl server.

    This is the reference's deployment shape — KvStore peer sessions are
    thrift clients of the peer's OpenrCtrlCpp service (KvStore.h:460-466);
    here they are OpenrCtrlClient connections to the peer's framed-JSON
    ctrl server, targeted via the PeerSpec (peer_addr, ctrl_port) that
    LinkMonitor learned from the Spark handshake.

    KvStoreDb registers/unregisters specs via the duck-typed
    `register_peer`/`unregister_peer` hooks on peer add/del.  Connections
    are cached per peer and torn down on failure so the KvStore's backoff
    machinery drives reconnects.
    """

    def __init__(self, tls=None, clock: Optional[Clock] = None, counters=None) -> None:
        from openr_tpu.common.runtime import CounterMap, WallClock

        #: TlsConfig for peer sessions — peers' ctrl servers must run the
        #: same TLS posture (Main.cpp:399-416: one thrift server serves
        #: both operators and KvStore peers, so one cert config covers both)
        self.tls = tls
        self._specs: Dict[str, Tuple[str, int]] = {}
        self._clients: Dict[str, object] = {}
        #: strong refs to detached close() tasks (loop refs are weak)
        self._close_tasks: Set[object] = set()
        #: per-peer dial locks so two concurrent RPCs to an un-cached peer
        #: can't both connect (the loser's connection would leak) — per
        #: peer, not global, so one blackholing peer can't head-of-line
        #: block dials to healthy peers
        self._connect_locks: Dict[str, object] = {}
        #: clock/counters normally arrive via bind_node (OpenrNode wires
        #: its own in its constructor); bare transports get local defaults
        self.clock: Clock = clock if clock is not None else WallClock()
        self.counters = counters if counters is not None else CounterMap()
        #: per-peer session breakers (openr_tpu.resilience): N consecutive
        #: RPC/dial failures open the circuit — calls short-circuit into
        #: KvStoreTransportError without redialing until the jittered hold
        #: elapses, then ONE half-open probe RPC re-establishes trust.
        #: KvStore's own retry/backoff machinery drives the probes.
        self._breakers: Dict[str, object] = {}

    def bind_node(self, clock: Clock, counters) -> None:
        """Adopt the owning node's clock + counter namespace (called by
        OpenrNode: one daemon per session-ful transport instance)."""
        self.clock = clock
        self.counters = counters
        self._breakers.clear()  # re-key onto the adopted clock

    def _breaker(self, peer_node: str):
        br = self._breakers.get(peer_node)
        if br is None:
            import zlib

            from openr_tpu.resilience import CircuitBreaker

            br = self._breakers[peer_node] = CircuitBreaker(
                f"kv_peer.{peer_node}",
                self.clock,
                failure_threshold=3,
                backoff_initial_s=1.0,
                backoff_max_s=30.0,
                jitter_pct=0.1,
                seed=zlib.crc32(peer_node.encode()),
                counters=self.counters,
            )
        return br

    def _admit(self, peer_node: str):
        br = self._breaker(peer_node)
        if not br.allow_request():
            self.counters.bump("kvstore.transport.short_circuit")
            raise KvStoreTransportError(
                f"circuit open to {peer_node} "
                f"(probe in {br.time_until_probe_s():.3f}s)"
            )
        return br

    def breaker_gauges(self) -> Dict[str, float]:
        """Monitor gauge provider: fleet-level view of the per-peer
        session breakers (per-peer detail lives in breaker_status)."""
        states = [b.state for b in self._breakers.values()]
        return {
            "resilience.kv_transport.peers": float(len(self._breakers)),
            "resilience.kv_transport.open": float(
                sum(1 for s in states if s == "open")
            ),
            "resilience.kv_transport.half_open": float(
                sum(1 for s in states if s == "half_open")
            ),
            "resilience.kv_transport.opens": float(
                sum(b.num_opens for b in self._breakers.values())
            ),
            "resilience.kv_transport.probes": float(
                sum(b.num_probes for b in self._breakers.values())
            ),
        }

    def breaker_status(self) -> Dict[str, dict]:
        """Per-peer breaker detail for `get_resilience_status`."""
        return {
            peer: br.status() for peer, br in sorted(self._breakers.items())
        }

    # -- peer registry hooks (called by KvStoreDb) --------------------------

    def register_peer(self, peer_node: str, spec: PeerSpec) -> None:
        addr = spec.peer_addr or "127.0.0.1"
        target = (addr, spec.ctrl_port)
        if self._specs.get(peer_node) != target:
            self._specs[peer_node] = target
            self._drop_client(peer_node, reason="respec")

    def unregister_peer(self, peer_node: str) -> None:
        self._specs.pop(peer_node, None)
        # the dial lock is deliberately NOT popped: an in-flight dial may
        # hold it, and a re-registered peer must serialize behind that dial
        # or the loser's connection leaks (locks are bounded by peers seen)
        self._drop_client(peer_node, reason="unregister")
        self._breakers.pop(peer_node, None)

    def _drop_client(self, peer_node: str, reason: str = "replaced") -> None:
        client = self._clients.pop(peer_node, None)
        if client is not None:
            import asyncio

            # per-reason teardown accounting: which failure class is
            # churning sessions (`breeze monitor counters
            # kvstore.transport.`)
            self.counters.bump(f"kvstore.transport.teardown.{reason}")
            task = asyncio.ensure_future(client.close())
            self._close_tasks.add(task)

            def _done(t, tasks=self._close_tasks):
                tasks.discard(t)
                t.exception()

            task.add_done_callback(_done)

    async def close(self) -> None:
        for peer in list(self._clients):
            client = self._clients.pop(peer)
            try:
                await client.close()
            except Exception:  # noqa: BLE001
                pass

    async def _client(self, peer_node: str):
        import asyncio

        client = self._clients.get(peer_node)
        if client is not None:
            return client
        lock = self._connect_locks.setdefault(peer_node, asyncio.Lock())
        async with lock:
            client = self._clients.get(peer_node)  # raced winner?
            if client is not None:
                return client
            target = self._specs.get(peer_node)
            if target is None:
                raise KvStoreTransportError(f"no PeerSpec for {peer_node}")
            try:
                client = await self._dial(target[0], target[1])
            except OSError as e:
                self.counters.bump("kvstore.transport.connect_failures")
                raise KvStoreTransportError(
                    f"connect to {peer_node} {target} failed: {e}"
                ) from e
            self._clients[peer_node] = client
            return client

    async def _dial(self, host: str, port: int):
        from openr_tpu.ctrl.client import OpenrCtrlClient

        return await OpenrCtrlClient(host=host, port=port, tls=self.tls).connect()

    async def _call(self, peer_node: str, method: str, **params):
        br = self._admit(peer_node)
        try:
            client = await self._client(peer_node)
        except KvStoreTransportError:
            br.record_failure()
            raise
        try:
            result = await client.call(method, **params)
        except (OSError, RuntimeError) as e:
            br.record_failure()
            self._drop_client(
                peer_node,
                reason="os_error" if isinstance(e, OSError) else "rpc_error",
            )
            raise KvStoreTransportError(
                f"rpc {method} to {peer_node} failed: {e}"
            ) from e
        br.record_success()
        return result

    # -- KvStoreTransport surface -------------------------------------------

    async def get_key_vals_filtered_area(
        self, peer_node, area, key_val_hashes, sender_id
    ) -> Publication:
        wire = await self._call(
            peer_node,
            "kv_store_full_sync_area",
            area=area,
            key_val_hashes={k: list(v) for k, v in key_val_hashes.items()},
            sender_id=sender_id,
        )
        return Publication.from_wire(wire)

    async def set_key_vals(self, peer_node, area, publication, sender_id) -> None:
        await self._call(
            peer_node,
            "kv_store_set_key_vals",
            area=area,
            publication=publication.to_wire(),
            sender_id=sender_id,
        )

    async def send_dual_messages(
        self, peer_node, area, messages, sender_id
    ) -> None:
        await self._call(
            peer_node,
            "kv_store_dual_messages",
            area=area,
            messages=messages.to_wire(),
            sender_id=sender_id,
        )

    async def set_flood_topo_child(
        self, peer_node, area, root_id, child, set_child, sender_id
    ) -> None:
        await self._call(
            peer_node,
            "kv_store_flood_topo_set",
            area=area,
            root_id=root_id,
            child=child,
            set_child=set_child,
            sender_id=sender_id,
        )


class RocketKvStoreTransport(TcpKvStoreTransport):
    """Peer transport speaking the REFERENCE's wire protocol: fbthrift
    Rocket framing + Compact-serialized thrift structs.

    This is byte-for-byte the RPC shape a real openr node's KvStore
    expects from a peer (`KvStore.h:460-466`: thrift clients issuing
    getKvStoreKeyValsFilteredArea / setKvStoreKeyVals) — full sync sends
    hash digests in KeyDumpParams.keyValHashes, flood/finalize pushes
    KeySetParams.  Peers must serve a RocketCtrlServer on their ctrl
    port (`lsdb_rpc_transport: "rocket"`).

    DUAL flood-optimization PDUs have no RPC in the reference's
    KvStoreService IDL (the library is legacy there — SURVEY §2.1), so
    this transport rejects them; run the jsonrpc transport if DUAL
    flood trees are enabled.
    """

    async def _dial(self, host: str, port: int):
        from openr_tpu.common.tls import client_ssl_context
        from openr_tpu.interop.rocket import RocketClient

        return await RocketClient(
            host, port, ssl=client_ssl_context(self.tls)
        ).connect()

    async def _call_rocket(self, peer_node: str, method: str, args: dict):
        from openr_tpu.interop.ctrl_rocket import DeclaredError, rocket_call
        from openr_tpu.interop.rocket import RocketCodecError, RocketError

        br = self._admit(peer_node)
        try:
            client = await self._client(peer_node)
        except KvStoreTransportError:
            br.record_failure()
            raise
        try:
            result = await rocket_call(client, method, args)
        except DeclaredError as e:
            # server-side declared exception: the connection is healthy
            br.record_success()
            raise KvStoreTransportError(
                f"rpc {method} to {peer_node} failed: {e}"
            ) from e
        except RocketCodecError as e:
            # the PEER's response bytes are garbage — teardown + redial
            # stays inside the KvStoreTransport error contract (or the
            # sync task dies and the peer sticks in SYNCING forever).
            # Bare ValueError is deliberately NOT caught any more: a
            # ValueError out of OUR encode path is a programming bug and
            # must crash loud, not be recycled as a transport blip.
            br.record_failure()
            self._drop_client(peer_node, reason="codec")
            raise KvStoreTransportError(
                f"rpc {method} to {peer_node} failed: {e}"
            ) from e
        except TimeoutError as e:
            br.record_failure()
            self._drop_client(peer_node, reason="timeout")
            raise KvStoreTransportError(
                f"rpc {method} to {peer_node} failed: {e}"
            ) from e
        except (OSError, RocketError) as e:
            br.record_failure()
            self._drop_client(
                peer_node,
                reason="os_error" if isinstance(e, OSError) else "rocket",
            )
            raise KvStoreTransportError(
                f"rpc {method} to {peer_node} failed: {e}"
            ) from e
        br.record_success()
        return result

    # -- KvStoreTransport surface ------------------------------------------

    async def get_key_vals_filtered_area(
        self, peer_node, area, key_val_hashes, sender_id
    ) -> Publication:
        from openr_tpu.interop.openr_wire import publication_from_wire_obj

        hashes = {
            k: {
                "version": v[0],
                "originatorId": v[1],
                **({"hash": v[2]} if v[2] is not None else {}),
            }
            for k, v in key_val_hashes.items()
        }
        wire = await self._call_rocket(
            peer_node,
            "getKvStoreKeyValsFilteredArea",
            {
                "filter": {"keyValHashes": hashes, "senderId": sender_id},
                "area": area,
            },
        )
        return publication_from_wire_obj(wire or {})

    async def set_key_vals(self, peer_node, area, publication, sender_id) -> None:
        from openr_tpu.interop.openr_wire import publication_to_wire_obj

        pub = publication_to_wire_obj(publication)
        set_params: dict = {
            "keyVals": pub.get("keyVals") or {},
            "senderId": sender_id,
        }
        if pub.get("nodeIds") is not None:
            set_params["nodeIds"] = pub["nodeIds"]
        if pub.get("timestamp_ms") is not None:
            set_params["timestamp_ms"] = pub["timestamp_ms"]
        await self._call_rocket(
            peer_node,
            "setKvStoreKeyVals",
            {"setParams": set_params, "area": area},
        )

    async def send_dual_messages(
        self, peer_node, area, messages, sender_id
    ) -> None:
        raise KvStoreTransportError(
            "DUAL PDUs have no RPC in the reference KvStoreService IDL; "
            "use lsdb_rpc_transport jsonrpc for flood optimization"
        )

    async def set_flood_topo_child(
        self, peer_node, area, root_id, child, set_child, sender_id
    ) -> None:
        raise KvStoreTransportError(
            "flood-topo RPCs are not part of the rocket peer surface; "
            "use lsdb_rpc_transport jsonrpc for flood optimization"
        )
