"""KvStoreSnooper — live-subscribe to a remote node's KvStore stream.

Reference parity: openr/kvstore/tools/KvStoreSnooper.cpp: attach to a
node's ctrl server, take the full snapshot, then print every delta
publication as it floods through the store.

Usage:
    python -m openr_tpu.kvstore.tools.snooper --port 2018 [--prefix adj:]
"""

from __future__ import annotations

import argparse
import asyncio
from typing import List, Optional

from openr_tpu.ctrl.client import OpenrCtrlClient


class KvStoreSnooper:
    """Programmatic snooper: `snoop()` yields (is_snapshot, key, value-dict)
    tuples; the CLI main pretty-prints them."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2018,
        key_prefixes: Optional[List[str]] = None,
        area: str = "0",
    ) -> None:
        self.host = host
        self.port = port
        self.key_prefixes = key_prefixes or []
        self.area = area

    async def snoop(self):
        async with OpenrCtrlClient(host=self.host, port=self.port) as client:
            first = True
            stream = client.stream(
                "subscribe_and_get_kv_store",
                key_prefixes=self.key_prefixes,
                areas=[self.area],
            )
            async for pub in stream:
                for key, value in (pub.get("key_vals") or {}).items():
                    yield first, key, value
                for key in pub.get("expired_keys") or []:
                    yield first, key, None
                first = False


async def _amain(args: argparse.Namespace) -> None:
    snooper = KvStoreSnooper(
        host=args.host,
        port=args.port,
        key_prefixes=[args.prefix] if args.prefix else [],
        area=args.area,
    )
    async for is_snapshot, key, value in snooper.snoop():
        tag = "SNAP" if is_snapshot else "DELTA"
        if value is None:
            print(f"[{tag}] {key} EXPIRED")
        else:
            print(
                f"[{tag}] {key} v={value.get('version')} "
                f"from={value.get('originator_id')} ttl={value.get('ttl')}"
            )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2018)
    p.add_argument("--prefix", default="", help="key-prefix filter")
    p.add_argument("--area", default="0")
    try:
        asyncio.run(_amain(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
