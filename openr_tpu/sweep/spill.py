"""Bounded result spill + the resumable checkpoint manifest.

A 100k-scenario sweep produces result rows that must never be
host-resident in bulk (millions of rows at fleet scale).  Rows stream
into JSONL *segments* under the sweep's spill directory, rotated every
``segment_rows`` rows, with an ``index.json`` describing every SEALED
segment (row count + sha256).  The online reducer consumes rows as they
are produced; nothing re-reads the spill on the happy path.

**Checkpoint commit ordering** (the resume invariant, enforced here and
documented in docs/Developer_Guide.md): a shard is only recorded in
``checkpoint.json`` after its rows are durably in the spill (written,
flushed, fsynced).  Both the index and the checkpoint are replaced
atomically (tmp + rename).  A killed sweep therefore resumes from the
last COMMITTED shard: rows of a half-written shard may exist in the
spill, but they are filtered out on resume because every row carries
its shard id and only committed shard ids are replayed.

Only this package mutates spill/checkpoint state — orlint's
``sweep-spill-ownership`` rule enforces it statically (the mutators are
``spill_rows`` / ``seal`` / ``commit_shard`` / ``reset``).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterator, List, Optional

from openr_tpu.sweep.scenario import canonical_json

INDEX_NAME = "index.json"
CHECKPOINT_NAME = "checkpoint.json"
SEGMENT_FMT = "rows-{:05d}.jsonl"


def _atomic_write(path: str, text: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SpillWriter:
    """Append-only JSONL segment writer with an atomic index."""

    def __init__(self, directory: str, segment_rows: int = 8192) -> None:
        if segment_rows < 1:
            raise ValueError("segment_rows must be >= 1")
        self.directory = directory
        self.segment_rows = segment_rows
        os.makedirs(directory, exist_ok=True)
        self._segments: List[dict] = []
        self._seg_index = 0
        self._seg_rows = 0
        self._seg_hash = hashlib.sha256()
        self._seg_file = None
        self.rows_written = 0
        self.bytes_written = 0
        #: high-watermark of rows held in host memory at once (one
        #: shard's batch) — the bench records it to prove the spill
        #: keeps the sweep out of host-resident-rows territory
        self.peak_host_rows = 0
        self._load_index()

    # -- index -------------------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.directory, INDEX_NAME)

    def _load_index(self) -> None:
        try:
            with open(self._index_path()) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        self._segments = list(doc.get("segments", []))
        self._seg_index = len(self._segments)
        self.rows_written = sum(s["rows"] for s in self._segments)
        self.bytes_written = sum(s["bytes"] for s in self._segments)

    def _write_index(self) -> None:
        _atomic_write(
            self._index_path(),
            canonical_json(
                {
                    "segments": self._segments,
                    "segment_rows": self.segment_rows,
                }
            ),
        )

    # -- mutators (sweep-package-owned; orlint sweep-spill-ownership) ------

    def spill_rows(self, rows: List[dict]) -> None:
        """Append one shard's rows (canonical JSONL), rotating segments
        at the row bound; flush + fsync before returning so a
        subsequent checkpoint commit never references volatile rows."""
        self.peak_host_rows = max(self.peak_host_rows, len(rows))
        for row in rows:
            if self._seg_file is None:
                self._open_segment()
            line = canonical_json(row) + "\n"
            data = line.encode()
            self._seg_file.write(line)
            self._seg_hash.update(data)
            self._seg_rows += 1
            self.rows_written += 1
            self.bytes_written += len(data)
            if self._seg_rows >= self.segment_rows:
                self.seal()
        if self._seg_file is not None:
            self._seg_file.flush()
            os.fsync(self._seg_file.fileno())

    def _open_segment(self) -> None:
        name = SEGMENT_FMT.format(self._seg_index)
        self._seg_name = name
        self._seg_file = open(os.path.join(self.directory, name), "w")
        self._seg_rows = 0
        self._seg_hash = hashlib.sha256()

    def seal(self) -> None:
        """Close the open segment and record it in the index."""
        if self._seg_file is None:
            return
        self._seg_file.flush()
        os.fsync(self._seg_file.fileno())
        self._seg_file.close()
        self._segments.append(
            {
                "name": self._seg_name,
                "rows": self._seg_rows,
                "bytes": os.path.getsize(
                    os.path.join(self.directory, self._seg_name)
                ),
                "sha256": self._seg_hash.hexdigest(),
            }
        )
        self._seg_file = None
        self._seg_index += 1
        self._seg_rows = 0
        self._write_index()

    def close(self) -> None:
        self.seal()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        return {
            "rows": self.rows_written,
            "bytes": self.bytes_written,
            "segments_sealed": len(self._segments),
            "open_segment_rows": self._seg_rows,
            "peak_host_rows": self.peak_host_rows,
        }


class SpillReader:
    """Stream rows back out of a spill directory (resume replay and the
    summary/offline analysis path) — one row at a time, never a bulk
    load."""

    def __init__(self, directory: str) -> None:
        self.directory = directory

    def segment_names(self) -> List[str]:
        sealed = []
        try:
            with open(os.path.join(self.directory, INDEX_NAME)) as f:
                sealed = [s["name"] for s in json.load(f)["segments"]]
        except (OSError, ValueError, KeyError):
            pass
        # the open (unsealed) segment, if any, sorts after the sealed
        # ones by construction of the name format
        try:
            names = sorted(
                n
                for n in os.listdir(self.directory)
                if n.startswith("rows-") and n.endswith(".jsonl")
            )
        except OSError:
            names = []
        return sealed + [n for n in names if n not in sealed]

    def rows(self, shard_filter=None) -> Iterator[dict]:
        """Yield rows, optionally filtered to a set of shard ids (the
        resume replay reads only COMMITTED shards' rows)."""
        for name in self.segment_names():
            try:
                f = open(os.path.join(self.directory, name))
            except OSError:
                continue
            with f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a killed run
                    if (
                        shard_filter is not None
                        and row.get("shard") not in shard_filter
                    ):
                        continue
                    yield row


class CheckpointManifest:
    """The committed-shard ledger a killed sweep resumes from."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.doc: Optional[dict] = None
        self._load()

    def _path(self) -> str:
        return os.path.join(self.directory, CHECKPOINT_NAME)

    def _load(self) -> None:
        try:
            with open(self._path()) as f:
                self.doc = json.load(f)
        except (OSError, ValueError):
            self.doc = None

    # -- mutators (sweep-package-owned; orlint sweep-spill-ownership) ------

    def reset(self, sweep_id: str, set_hash: str, spec: dict, total: int) -> None:
        """Begin a fresh sweep: any prior manifest for a DIFFERENT
        scenario set is replaced."""
        self.doc = {
            "sweep_id": sweep_id,
            "set_hash": set_hash,
            "spec": spec,
            "total_scenarios": total,
            "shards": {},
        }
        _atomic_write(self._path(), canonical_json(self.doc))

    def commit_shard(self, shard_id: int, meta: dict) -> None:
        """Record one COMPLETED shard.  Callers must have spilled the
        shard's rows (flushed + fsynced) first — commit ordering is the
        resume invariant."""
        if self.doc is None:
            raise RuntimeError("commit_shard before reset()")
        self.doc["shards"][str(shard_id)] = meta
        _atomic_write(self._path(), canonical_json(self.doc))

    # -- read surface ------------------------------------------------------

    def matches(self, set_hash: str) -> bool:
        return self.doc is not None and self.doc.get("set_hash") == set_hash

    def completed_shards(self) -> Dict[int, dict]:
        if self.doc is None:
            return {}
        return {int(k): v for k, v in self.doc.get("shards", {}).items()}
