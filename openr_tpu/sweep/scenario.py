"""Declarative scenario grammar for capacity-planning sweeps.

A *scenario* is one counterfactual world the sweep solves: a set of
simultaneous link failures, evaluated under a *world variant* — a
drain-state assignment (nodes taken out of transit, the maintenance
shape) crossed with a metric perturbation (links whose metrics are
scaled, the cost-out shape).  The grammar enumerates the classic
capacity-planning cross product:

    (all single-link failures  +  bounded k-failure-domain combos)
        x  drain states  x  metric perturbations

Identity is **content-addressed**: every scenario's hash is the sha256
of its canonical JSON content (node NAMES and link PAIRS, never slot or
link ids), so two enumerations of the same grammar over the same LSDB
produce the same scenario set whatever order they walked it in — the
executor sorts by ``(world key, hash)`` and shards contiguously, which
is what makes a checkpointed sweep resumable and its ranked summary
byte-reproducible.

k-failure-domain combinations treat each NODE as a failure domain (its
incident links fail together — the node-failure shape); the explicit
bound draws a deterministic seeded sample over the sorted domain
universe, so the combination subset is a pure function of
``(domains, k, bound, seed)`` and never of enumeration order.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import random
import re
from typing import Dict, List, Optional, Sequence, Tuple


def canonical_json(doc) -> str:
    """THE canonical encoding for everything the sweep hashes or spills
    (sorted keys, no whitespace): two runs agree byte for byte or not
    at all."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def content_hash(doc) -> str:
    return hashlib.sha256(canonical_json(doc).encode()).hexdigest()


@dataclasses.dataclass(frozen=True)
class World:
    """One (drain state, metric perturbation) variant the scenario's
    failures are evaluated under."""

    #: node names taken out of transit (hard drain), sorted
    drained_nodes: Tuple[str, ...] = ()
    #: (pattern, factor): metrics of links whose BOTH endpoints
    #: full-match the regex are scaled by factor; None = identity
    metric: Optional[Tuple[str, float]] = None

    def content(self) -> dict:
        return {
            "drained_nodes": list(self.drained_nodes),
            "metric": (
                None
                if self.metric is None
                else {"pattern": self.metric[0], "factor": self.metric[1]}
            ),
        }

    def key(self) -> str:
        """Stable world label (groups scenarios for shard packing and
        the per-world summary rollup)."""
        drain = ",".join(self.drained_nodes) or "-"
        if self.metric is None:
            metric = "-"
        else:
            metric = f"{self.metric[0]}x{self.metric[1]:g}"
        return f"drain[{drain}]|metric[{metric}]"


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One content-addressed counterfactual."""

    world: World
    #: failed links as sorted (n1, n2) name pairs, sorted
    failed_links: Tuple[Tuple[str, str], ...]
    #: failure domains (node names) this scenario is the combination
    #: of; empty for plain link-failure scenarios
    domains: Tuple[str, ...] = ()

    def content(self) -> dict:
        return {
            "world": self.world.content(),
            "failed_links": [list(p) for p in self.failed_links],
            "domains": list(self.domains),
        }

    @property
    def hash(self) -> str:
        h = self.__dict__.get("_hash")
        if h is None:
            h = content_hash(self.content())
            # frozen dataclass: route around __setattr__ for the memo
            object.__setattr__(self, "_hash", h)
        return h


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """The declarative grammar (config defaults live in
    ``sweep_config``; ``start_sweep`` params override per sweep)."""

    #: enumerate every single-link failure per world
    single_link_failures: bool = True
    #: bound on enumerated single-link failures per world: the first N
    #: pairs in canonical (sorted) order; 0 = no bound.  The protection
    #: tier's ``max_links`` maps here — links past the bound simply get
    #: no patch (counted as ``protection.fallback.miss`` at apply time)
    max_single_link_scenarios: int = 0
    #: failure-domain combination order (nodes as domains); < 2 = off
    combo_k: int = 0
    #: explicit bound on enumerated k-combinations per world (0 = none
    #: even when combo_k >= 2 — the bound is mandatory by construction)
    max_combo_scenarios: int = 0
    #: seeds the deterministic combination draw
    combo_seed: int = 0
    #: drain-state variants; the identity (no drain) world must be
    #: listed explicitly if wanted — the default is identity only
    drain_node_sets: Tuple[Tuple[str, ...], ...] = ((),)
    #: metric perturbation variants as (pattern, factor); the identity
    #: variant is always included
    metric_perturbations: Tuple[Tuple[str, float], ...] = ()
    #: shared-risk link groups as failure domains: ``(name, ((a, b),
    #: ...))`` entries whose member links fail TOGETHER — one scenario
    #: per group per world, intersected with the live link pairs at
    #: enumeration time (a group none of whose links exist is skipped).
    #: Configured via ``sweep_config.srlg_groups``; the protection tier
    #: mints per-SRLG patches from exactly these scenarios.
    srlg_groups: Tuple[Tuple[str, Tuple[Tuple[str, str], ...]], ...] = ()
    #: restrict enumeration to the worlds whose ``World.key()`` is
    #: listed (sorted, deduplicated); empty = every world.  The fleet
    #: coordinator slices one fleet-wide grammar into per-node
    #: sub-sweeps with exactly this knob — each node enumerates the
    #: SAME worlds the fleet assignment gave it, and the slice identity
    #: is content-addressed like everything else.
    world_filter: Tuple[str, ...] = ()

    def content(self) -> dict:
        doc = {
            "single_link_failures": self.single_link_failures,
            "combo_k": self.combo_k,
            "max_combo_scenarios": self.max_combo_scenarios,
            "combo_seed": self.combo_seed,
            "drain_node_sets": [list(s) for s in self.drain_node_sets],
            "metric_perturbations": [
                {"pattern": p, "factor": f}
                for p, f in self.metric_perturbations
            ],
        }
        if self.max_single_link_scenarios:
            doc["max_single_link_scenarios"] = self.max_single_link_scenarios
        if self.srlg_groups:
            # only present when configured, so every pre-SRLG grammar's
            # content hash (and thus its resumable checkpoints) is
            # preserved verbatim — regression-tested
            doc["srlg_groups"] = [
                {"name": name, "links": [list(p) for p in pairs]}
                for name, pairs in self.srlg_groups
            ]
        if self.world_filter:
            # only present when configured (the srlg_groups discipline):
            # every unfiltered grammar's content hash — and thus its
            # resumable checkpoints — is preserved verbatim
            doc["world_filter"] = list(self.world_filter)
        return doc

    @classmethod
    def from_params(cls, config, params: Optional[dict]) -> "ScenarioSpec":
        """Spec from the ``sweep_config`` defaults overridden by a
        ``start_sweep`` params dict (the ctrl/CLI surface)."""
        params = dict(params or {})
        drain = params.get(
            "drain_node_sets",
            [list(s) for s in getattr(config, "drain_node_sets", [[]])],
        )
        metric = params.get("metric_perturbations")
        if metric is None:
            metric = [
                {"pattern": m.pattern, "factor": m.factor}
                for m in getattr(config, "metric_perturbations", [])
            ]
        srlg = params.get("srlg_groups")
        if srlg is None:
            srlg = [
                {"name": g.name, "links": [list(p) for p in g.links]}
                for g in getattr(config, "srlg_groups", [])
            ]
        return cls(
            single_link_failures=bool(
                params.get("single_link_failures", True)
            ),
            combo_k=int(params.get("combo_k", config.combo_k)),
            max_combo_scenarios=int(
                params.get(
                    "max_combo_scenarios", config.max_combo_scenarios
                )
            ),
            combo_seed=int(params.get("combo_seed", 0)),
            drain_node_sets=tuple(
                tuple(sorted(set(map(str, s)))) for s in drain
            )
            or ((),),
            metric_perturbations=tuple(
                (str(m["pattern"]), float(m["factor"])) for m in metric
            ),
            srlg_groups=normalize_srlg_groups(srlg),
            world_filter=tuple(
                sorted(set(map(str, params.get("world_filter", ()))))
            ),
        )


def normalize_srlg_groups(groups) -> Tuple:
    """Canonical SRLG tuple form from config objects, params dicts or
    already-normalized ``(name, pairs)`` tuples (idempotent): per group
    the member pairs are endpoint-sorted, deduplicated and sorted;
    groups sort by name — so one risk-group definition has exactly one
    content identity however it was spelled."""
    out = []
    for g in groups or ():
        if isinstance(g, dict):
            name, links = str(g["name"]), g["links"]
        elif isinstance(g, (tuple, list)):
            name, links = str(g[0]), g[1]
        else:
            name, links = str(g.name), g.links
        pairs = tuple(
            sorted(set(tuple(sorted(map(str, p))) for p in links))
        )
        out.append((name, pairs))
    out.sort()
    return tuple(out)


def srlg_domain(name: str) -> str:
    """The failure-domain label an SRLG scenario carries — also the
    protection table's patch key for a per-SRLG patch."""
    return f"srlg:{name}"


def worlds_of(spec: ScenarioSpec) -> List[World]:
    """The world variants, in deterministic grammar order (drain outer,
    metric inner; identity metric first)."""
    metrics: List[Optional[Tuple[str, float]]] = [None]
    metrics += [m for m in spec.metric_perturbations]
    out: List[World] = []
    for drain in spec.drain_node_sets:
        for metric in metrics:
            out.append(World(tuple(sorted(drain)), metric))
    return out


def _sorted_pairs(pairs: Sequence[Tuple[str, str]]) -> List[Tuple[str, str]]:
    return sorted(tuple(sorted(p)) for p in pairs)


def enumerate_scenarios(
    spec: ScenarioSpec,
    link_pairs: Sequence[Tuple[str, str]],
    node_links: Optional[Dict[str, Sequence[Tuple[str, str]]]] = None,
) -> List[Scenario]:
    """Deterministic enumeration over the live LSDB's link pairs.

    ``link_pairs``: the (n1, n2) node pairs carrying at least one link.
    ``node_links``: node -> incident pairs (the failure-domain map);
    derived from ``link_pairs`` when omitted.  The result is sorted by
    ``(world key, scenario hash)`` — the canonical execution order."""
    pairs = _sorted_pairs(set(tuple(sorted(p)) for p in link_pairs))
    if node_links is None:
        node_links = {}
        for a, b in pairs:
            node_links.setdefault(a, []).append((a, b))
            node_links.setdefault(b, []).append((a, b))
    out: List[Scenario] = []
    flt = set(spec.world_filter)
    for world in worlds_of(spec):
        if flt and world.key() not in flt:
            continue
        if spec.single_link_failures:
            bound = spec.max_single_link_scenarios
            for p in (pairs[:bound] if bound else pairs):
                out.append(Scenario(world, (p,)))
        if spec.srlg_groups:
            live = set(pairs)
            for name, group_pairs in spec.srlg_groups:
                failed = tuple(
                    sorted(p for p in group_pairs if p in live)
                )
                if not failed:
                    continue
                out.append(
                    Scenario(world, failed, domains=(srlg_domain(name),))
                )
        if spec.combo_k >= 2 and spec.max_combo_scenarios > 0:
            domains = sorted(node_links)
            combos = _draw_combos(
                domains,
                spec.combo_k,
                spec.max_combo_scenarios,
                spec.combo_seed,
            )
            for combo in combos:
                failed = set()
                for n in combo:
                    failed.update(
                        tuple(sorted(p)) for p in node_links[n]
                    )
                if not failed:
                    continue
                out.append(
                    Scenario(
                        world,
                        tuple(sorted(failed)),
                        domains=tuple(combo),
                    )
                )
    out.sort(key=lambda s: (s.world.key(), s.hash))
    return out


def _draw_combos(
    domains: List[str], k: int, bound: int, seed: int
) -> List[Tuple[str, ...]]:
    """A deterministic sample of at most ``bound`` k-combinations over
    the SORTED domain list: exhaustive when the universe fits the
    bound, else a seeded draw — a pure function of (domains, k, bound,
    seed), independent of any enumeration order."""
    n = len(domains)
    if n < k:
        return []
    total = 1
    for i in range(k):
        total = total * (n - i) // (i + 1)
    if total <= bound:
        return [tuple(c) for c in itertools.combinations(domains, k)]
    rng = random.Random(
        int.from_bytes(
            hashlib.sha256(
                canonical_json([domains, k, bound, seed]).encode()
            ).digest()[:8],
            "big",
        )
    )
    seen = set()
    out: List[Tuple[str, ...]] = []
    # rejection draw: k distinct indices per combo; the universe is
    # far larger than the bound here, so collisions are rare
    while len(out) < bound:
        combo = tuple(sorted(rng.sample(range(n), k)))
        if combo in seen:
            continue
        seen.add(combo)
        out.append(tuple(domains[i] for i in combo))
    out.sort()
    return out


def scenario_set_hash(spec: ScenarioSpec, scenarios: List[Scenario]) -> str:
    """Content address of the WHOLE sweep: the grammar plus every
    scenario hash in canonical order.  The checkpoint manifest pins it,
    so a resume against a drifted grammar or LSDB is refused instead of
    silently mixing two different sweeps' rows."""
    h = hashlib.sha256()
    h.update(canonical_json(spec.content()).encode())
    for s in scenarios:
        h.update(s.hash.encode())
    return h.hexdigest()


def metric_matcher(pattern: str):
    """Compiled full-match predicate over a link's endpoint pair."""
    rx = re.compile(pattern)
    return lambda a, b: rx.fullmatch(a) is not None and rx.fullmatch(b) is not None
