"""Online ranked-risk reducer — the sweep's summary in bounded memory.

Rows stream in shard by shard; the reducer keeps only:

* per-link criticality aggregates (O(links): worst/total routes
  withdrawn, scenario counts) for the criticality ranking;
* the SPOF set (links whose SINGLE failure withdraws at least one
  route in ANY world — the classic single-point-of-failure list);
* a bounded top-K worst-scenario table (worst-case reachability loss);
* per-world and whole-sweep tallies.

Every ranking is deterministically tie-broken (count desc, then link /
hash asc), and the summary is pure row content — no clocks, no ids —
so an uninterrupted run and a kill-and-resume run produce byte-equal
summaries, which the resume tests and the bench assert via
``summary_digest``.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

from openr_tpu.sweep.scenario import canonical_json


def _link_key(pair) -> str:
    return "|".join(sorted(map(str, pair)))


def world_deltas(group, deltas):
    """Per-scenario route-delta rows of ONE drained single-area world
    group, as a reusable iterator — the single pass both the reducer's
    row extraction and the protection tier's patch compaction consume,
    so riding consumers never force a second device sweep.

    ``group`` is an executor world group (``items`` + parallel
    ``errors``); ``deltas`` is its drained
    :class:`openr_tpu.ops.sweep_select.SweepRouteDeltas`.  Yields
    ``(scenario, solve, row, delta)`` tuples in scenario order:

    * ``solve == "error"``: the scenario's failed links weren't
      resolvable against this context (topology drifted) — ``row`` is 0
      and ``delta`` is None;
    * ``solve == "alias"``: the failure aliased to the base world
      (zero route delta) — ``row`` is 0 and ``delta`` is None;
    * ``solve == "device"``: ``row`` is the scenario's unique snapshot
      row (> 0; scenarios may share one) and ``delta`` is its
      ``deltas_of_row`` slice ``(p_idx, valid, metric, lanes)``.
    """
    for k, (scen, is_err) in enumerate(zip(group["items"], group["errors"])):
        if is_err:
            yield scen, "error", 0, None
            continue
        r = int(deltas.snap_row[k])
        if r == 0:
            yield scen, "alias", 0, None
        else:
            yield scen, "device", r, deltas.deltas_of_row(r)


class SweepReducer:
    def __init__(self, top_k: int = 64) -> None:
        self.top_k = top_k
        self.scenarios = 0
        self.zero_delta = 0
        self.error_rows = 0
        self.device_rows = 0
        self.alias_rows = 0
        self.total_withdrawn = 0
        self.total_changed = 0
        self.by_world: Dict[str, dict] = {}
        #: link key -> aggregates (bounded by the link universe)
        self.links: Dict[str, dict] = {}
        #: link keys whose single-link failure withdrew routes
        self.spof: set = set()
        #: bounded worst-scenario table entries:
        #: (withdrawn, changed, hash, world, failure)
        self._worst: List[tuple] = []

    # -- feeding -----------------------------------------------------------

    def feed(self, rows: List[dict]) -> None:
        for row in rows:
            self._feed_one(row)

    def _feed_one(self, row: dict) -> None:
        self.scenarios += 1
        world = row.get("world", "-")
        w = self.by_world.setdefault(
            world,
            {"scenarios": 0, "withdrawn": 0, "changed": 0, "worst": 0},
        )
        w["scenarios"] += 1
        if row.get("solve") == "error":
            self.error_rows += 1
            return
        if row.get("solve") == "alias":
            self.alias_rows += 1
        else:
            self.device_rows += 1
        withdrawn = int(row.get("withdrawn", 0))
        changed = int(row.get("changed", 0))
        if changed == 0:
            self.zero_delta += 1
        self.total_withdrawn += withdrawn
        self.total_changed += changed
        w["withdrawn"] += withdrawn
        w["changed"] += changed
        w["worst"] = max(w["worst"], withdrawn)
        failure = row.get("failure", [])
        single = len(failure) == 1 and not row.get("domains")
        for pair in failure:
            key = _link_key(pair)
            agg = self.links.setdefault(
                key,
                {
                    "scenarios": 0,
                    "worst_withdrawn": 0,
                    "total_withdrawn": 0,
                    "single_withdrawn": 0,
                },
            )
            agg["scenarios"] += 1
            agg["total_withdrawn"] += withdrawn
            agg["worst_withdrawn"] = max(agg["worst_withdrawn"], withdrawn)
            if single:
                agg["single_withdrawn"] = max(
                    agg["single_withdrawn"], withdrawn
                )
        if single and withdrawn > 0:
            self.spof.add(_link_key(failure[0]))
        if withdrawn > 0 or changed > 0:
            self._note_worst(
                (
                    -withdrawn,
                    -changed,
                    row.get("hash", ""),
                    world,
                    [list(p) for p in failure],
                )
            )

    def _note_worst(self, entry: tuple) -> None:
        # small K: insertion into a sorted list beats a heap with
        # deterministic tie-breaking for free
        self._worst.append(entry)
        self._worst.sort()
        del self._worst[self.top_k :]

    # -- the ranked summary ------------------------------------------------

    def summary(self) -> dict:
        ranking = sorted(
            self.links.items(),
            key=lambda kv: (
                -kv[1]["worst_withdrawn"],
                -kv[1]["total_withdrawn"],
                kv[0],
            ),
        )[: self.top_k]
        worst = [
            {
                "withdrawn": -e[0],
                "changed": -e[1],
                "hash": e[2],
                "world": e[3],
                "failure": e[4],
            }
            for e in self._worst
        ]
        return {
            "scenarios": self.scenarios,
            "zero_delta": self.zero_delta,
            "error_rows": self.error_rows,
            "device_rows": self.device_rows,
            "alias_rows": self.alias_rows,
            "total_withdrawn": self.total_withdrawn,
            "total_changed": self.total_changed,
            "worst_case": (worst[0] if worst else None),
            "worst_scenarios": worst,
            "spof_links": sorted(self.spof),
            "criticality": [
                {"link": k.split("|"), **v} for k, v in ranking
            ],
            "worlds": {
                k: dict(v) for k, v in sorted(self.by_world.items())
            },
        }

    def summary_digest(self) -> str:
        """sha256 of the canonical summary — the byte-identity handle
        the resume proof compares."""
        return hashlib.sha256(
            canonical_json(self.summary()).encode()
        ).hexdigest()


def replay_reducer(
    reader, completed: set, top_k: int = 64
) -> Optional[SweepReducer]:
    """Rebuild a reducer from the spill's COMMITTED shards (the resume
    path: one streaming pass, bounded memory).  Returns the reducer and
    relies on the caller to verify replayed row counts against the
    checkpoint manifest."""
    red = SweepReducer(top_k=top_k)
    for row in reader.rows(shard_filter=completed):
        red._feed_one(row)
    return red
