"""Capacity-planning sweep orchestrator (ISSUE 14).

BENCH_r03's ~249k raw device solves/s existed only as hand-rolled
what-if engine batches; this package turns that throughput into a
*capacity-planning product* (ROADMAP "what-if planning as a product"):

* :mod:`openr_tpu.sweep.scenario` — a declarative, deterministic
  scenario grammar (all single-link failures x drain states x metric
  perturbations; bounded k-failure-domain combinations), every scenario
  content-addressable by a stable hash so enumeration order never
  matters;
* :mod:`openr_tpu.sweep.spill` — bounded result spill (JSONL segments +
  index; rows are never host-resident in bulk) and the checkpoint
  manifest a killed sweep resumes from;
* :mod:`openr_tpu.sweep.reduce` — the online reducer maintaining the
  ranked risk summary (worst-case reachability loss, SPOF list,
  per-link criticality ranking) in bounded memory;
* :mod:`openr_tpu.sweep.executor` — the sharded executor: scenarios
  pack into committed per-device dispatches across the DevicePool's
  survivors (streamed drain, chip quarantine mid-sweep re-packs only
  the lost shard), planning rides the content-hash
  ``build_repair_plan_cached`` cache so prefix churn mid-sweep never
  restarts it, and each committed shard is spilled + checkpointed
  before the next begins;
* :mod:`openr_tpu.sweep.rows` — the scenario row differ shared with the
  streaming watch plane (what-if feeds emit per-scenario-row deltas);
* :mod:`openr_tpu.sweep.service` — the ``SweepService`` actor behind
  ``start_sweep`` / ``get_sweep_status`` / ``get_sweep_summary`` /
  ``cancel_sweep`` and ``breeze sweep run|status|summary|cancel``.

See docs/Sweeps.md for the grammar, the spill format and the resume
semantics; Developer_Guide.md for the invariants (content-hash
identity, checkpoint commit ordering).
"""

from openr_tpu.sweep.executor import SweepError, SweepExecutor, SweepInputs
from openr_tpu.sweep.reduce import SweepReducer
from openr_tpu.sweep.rows import diff_scenario_rows, scenario_row_key, scenario_rows
from openr_tpu.sweep.scenario import (
    Scenario,
    ScenarioSpec,
    World,
    enumerate_scenarios,
    scenario_set_hash,
)
from openr_tpu.sweep.service import SweepService
from openr_tpu.sweep.spill import CheckpointManifest, SpillReader, SpillWriter

__all__ = [
    "CheckpointManifest",
    "Scenario",
    "ScenarioSpec",
    "SpillReader",
    "SpillWriter",
    "SweepError",
    "SweepExecutor",
    "SweepInputs",
    "SweepReducer",
    "SweepService",
    "World",
    "diff_scenario_rows",
    "enumerate_scenarios",
    "scenario_row_key",
    "scenario_rows",
    "scenario_set_hash",
]
