"""Sharded, resumable scenario-sweep executor.

Scenarios (hash-sorted within their world, worlds contiguous) pack into
fixed-size shards; each shard is dispatched as COMMITTED per-device
work on one healthy DevicePool chip (round-robin over the survivors at
dispatch time), solved through the warm-start repair sweep
(:class:`~openr_tpu.ops.whatif.LinkFailureSweep` +
:class:`~openr_tpu.ops.sweep_select.SweepRouteSelector` — the BENCH_r03
throughput machinery) for single-area LSDBs, or through the multi-area
what-if kernel (:func:`~openr_tpu.ops.fleet_tables
.whatif_multi_area_tables`) for multi-area ones.  Up to ``inflight``
shards ride the streamed drain path at once (dispatch shard N+1 while
shard N's delta compaction is still on device; drains commit in FIFO
order so the spill layout is deterministic).

Resilience/resume contract:

* a shard whose dispatch or drain raises quarantines ITS chip through
  the governor (``record_stream_failure`` — the PR-11 streamed-failure
  path) and re-packs ONLY that shard onto the next survivor; committed
  shards are never re-run;
* after every committed shard the spill is durable and the checkpoint
  manifest records it, so a killed sweep resumes from the last
  committed shard: the resume replays committed rows from the spill
  into a fresh reducer (verifying counts against the manifest) and
  continues with the first uncommitted shard;
* planning rides the content-hash ``build_repair_plan_cached`` cache:
  a prefix-churn generation bump mid-sweep rebuilds the candidate
  tables but every world's repair plan is a cache hit (the topology
  content is unchanged), so the sweep never restarts planning.

Phase attribution: shard solves record under the
``pipeline.sweep_shard_solve`` phase (device-attributed, per-chip busy
time on the shared ledger), drains under ``pipeline.stream_drain``, row
decode under ``pipeline.decode``, and the reducer + spill under
``pipeline.sweep_reduce`` — the bench proves the sweep is device-bound
from exactly these histograms.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.sweep.reduce import SweepReducer, replay_reducer
from openr_tpu.sweep.scenario import (
    Scenario,
    ScenarioSpec,
    World,
    enumerate_scenarios,
    metric_matcher,
    scenario_set_hash,
)
from openr_tpu.sweep.spill import CheckpointManifest, SpillReader, SpillWriter


class SweepError(RuntimeError):
    """Sweep cannot start/continue (no LSDB, drained vantage, no
    surviving devices, spill/checkpoint disagreement on resume)."""


@dataclasses.dataclass
class SweepInputs:
    """Everything the executor reads from the decision plane.  Pulled
    fresh via ``inputs_fn`` before every context (re)build, so a
    generation bump mid-sweep is picked up at the next shard."""

    area_link_states: dict
    prefix_state: object
    change_seq: int
    root: str
    pool: object = None
    probe: object = None
    governor: object = None
    per_area_distance: bool = False


class _ShardHandle:
    """One in-flight shard: its dispatched world groups + bookkeeping."""

    __slots__ = ("shard_id", "groups", "device_index", "t0")

    def __init__(self, shard_id, groups, device_index, t0):
        self.shard_id = shard_id
        self.groups = groups
        self.device_index = device_index
        self.t0 = t0


class SweepExecutor:
    def __init__(
        self,
        inputs_fn: Callable[[], SweepInputs],
        spill_dir: str,
        clock=None,
        counters=None,
        shard_scenarios: int = 1024,
        segment_rows: int = 8192,
        top_k: int = 64,
        inflight: int = 2,
        engine_cache_entries: int = 8,
    ) -> None:
        from openr_tpu.common.runtime import CounterMap
        from openr_tpu.tracing.pipeline import disabled_probe

        if shard_scenarios < 1:
            raise ValueError("shard_scenarios must be >= 1")
        self.inputs_fn = inputs_fn
        self.spill_dir = spill_dir
        self.clock = clock
        self.counters = counters if counters is not None else CounterMap()
        self.shard_scenarios = shard_scenarios
        self.segment_rows = segment_rows
        self.top_k = top_k
        self.inflight_limit = max(1, inflight)
        self._engine_cache_entries = max(1, engine_cache_entries)
        self._probe = disabled_probe()
        self.spec: Optional[ScenarioSpec] = None
        self.scenarios: List[Scenario] = []
        self.set_hash = ""
        self.sweep_id = ""
        self.shards: List[Tuple[int, int, int]] = []
        self.completed: set = set()
        self.resumed_shards = 0
        self.reducer = SweepReducer(top_k=top_k)
        self.spill: Optional[SpillWriter] = None
        self.checkpoint: Optional[CheckpointManifest] = None
        self.cancelled = False
        #: per-(ctx epoch, world, chip) engine cache, LRU-bounded
        self._engines: "collections.OrderedDict" = collections.OrderedDict()
        self._ctx = None
        self._ctx_key = None
        self._ctx_epoch = 0
        self._rr = 0  # device round-robin cursor
        self.num_device_solves = 0
        self.num_repacked_shards = 0
        self.generations_observed: set = set()
        #: optional rider on the drained single-area deltas
        #: (ctx, shard_id, group, deltas) — the protection tier's patch
        #: compaction consumes the SAME drained pass the reducer's row
        #: extraction reads (reduce.world_deltas), never a second sweep
        self.delta_consumer = None
        #: optional per-shard durability rider, called between the spill
        #: append and the checkpoint commit (same crash discipline)
        self.commit_hook = None

    # -- preparation / resume ----------------------------------------------

    def prepare(self, spec: ScenarioSpec, resume: bool = True) -> dict:
        """Enumerate, shard, and (when a matching checkpoint exists)
        resume: committed shards are skipped and their rows replayed
        from the spill into the reducer.  Returns the prepare report."""
        inputs = self.inputs_fn()
        if not inputs.area_link_states:
            raise SweepError("no LSDB yet — nothing to sweep")
        for s in spec.drain_node_sets:
            if inputs.root in s:
                raise SweepError(
                    f"drain set {list(s)} drains the sweep vantage "
                    f"{inputs.root!r}"
                )
        self.spec = spec
        pairs = self._all_pairs(inputs)
        self.scenarios = enumerate_scenarios(spec, pairs)
        if not self.scenarios:
            raise SweepError("the grammar enumerates zero scenarios")
        self.set_hash = scenario_set_hash(spec, self.scenarios)
        self.sweep_id = self.set_hash[:16]
        self.shards = []
        for i, lo in enumerate(
            range(0, len(self.scenarios), self.shard_scenarios)
        ):
            self.shards.append(
                (i, lo, min(lo + self.shard_scenarios, len(self.scenarios)))
            )
        self.checkpoint = CheckpointManifest(self.spill_dir)
        if not (resume and self.checkpoint.matches(self.set_hash)):
            # fresh sweep: a clean spill.  Stale segments from an
            # earlier sweep in the same directory would otherwise be
            # appended to — and a LATER resume's shard-id replay could
            # collide with the old sweep's identically-numbered shards
            self._wipe_spill()
        self.spill = SpillWriter(
            self.spill_dir, segment_rows=self.segment_rows
        )
        self.completed = set()
        self.resumed_shards = 0
        self.reducer = SweepReducer(top_k=self.top_k)
        if resume and self.checkpoint.matches(self.set_hash):
            committed = self.checkpoint.completed_shards()
            self.completed = set(committed)
            self.resumed_shards = len(self.completed)
            if self.completed:
                self.reducer = replay_reducer(
                    SpillReader(self.spill_dir),
                    self.completed,
                    top_k=self.top_k,
                )
                expect = sum(m["rows"] for m in committed.values())
                if self.reducer.scenarios != expect:
                    raise SweepError(
                        f"spill/checkpoint disagree on resume: manifest "
                        f"says {expect} committed rows, spill replayed "
                        f"{self.reducer.scenarios}"
                    )
                self.counters.bump("sweep.resumes")
                self.counters.bump(
                    "sweep.resumed_shards", self.resumed_shards
                )
        else:
            self.checkpoint.reset(
                self.sweep_id,
                self.set_hash,
                spec.content(),
                len(self.scenarios),
            )
        return {
            "sweep_id": self.sweep_id,
            "set_hash": self.set_hash,
            "scenarios": len(self.scenarios),
            "shards": len(self.shards),
            "resumed_shards": self.resumed_shards,
        }

    def _wipe_spill(self) -> None:
        """Drop every spill segment + the index (fresh-sweep reset;
        the checkpoint itself is replaced by ``reset``)."""
        import os

        from openr_tpu.sweep.spill import INDEX_NAME

        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return
        for name in names:
            if name == INDEX_NAME or (
                name.startswith("rows-") and name.endswith(".jsonl")
            ):
                try:
                    os.unlink(os.path.join(self.spill_dir, name))
                except OSError:
                    pass

    @staticmethod
    def _all_pairs(inputs: SweepInputs) -> List[Tuple[str, str]]:
        pairs = set()
        for _area, ls in sorted(inputs.area_link_states.items()):
            for link in ls.all_links():
                pairs.add(tuple(sorted((link.n1, link.n2))))
        return sorted(pairs)

    def pending_shards(self) -> List[int]:
        return [s[0] for s in self.shards if s[0] not in self.completed]

    # -- context -----------------------------------------------------------

    def _context(self):
        """(Re)build the shared solve context when the generation moved.
        Keyed exactly like the what-if engines: (change_seq, per-area
        topology seq).  A prefix-churn bump re-encodes candidates but
        every world's repair plan is a ``build_repair_plan_cached``
        content-hash hit — the 'planning never restarts' property."""
        inputs = self.inputs_fn()
        key = (
            inputs.change_seq,
            tuple(
                (a, inputs.area_link_states[a].topology_seq)
                for a in sorted(inputs.area_link_states)
            ),
        )
        self.generations_observed.add(key)
        if self._ctx is not None and self._ctx_key == key:
            return self._ctx
        from openr_tpu.tracing.pipeline import disabled_probe

        self._probe = (
            inputs.probe if inputs.probe is not None else disabled_probe()
        )
        multi = len(inputs.area_link_states) > 1
        if multi:
            ctx = self._build_multi_context(inputs)
        else:
            ctx = self._build_single_context(inputs)
        ctx["inputs"] = inputs
        ctx["multi"] = multi
        self._ctx = ctx
        self._ctx_key = key
        self._ctx_epoch += 1
        self.counters.bump("sweep.context_builds")
        return ctx

    def _build_single_context(self, inputs: SweepInputs) -> dict:
        from openr_tpu.decision.whatif_api import build_pair_links
        from openr_tpu.ops.csr import encode_link_state, encode_prefix_candidates
        from openr_tpu.tracing import pipeline

        (area, ls), = inputs.area_link_states.items()
        with self._probe.phase(pipeline.ENCODE):
            topo = encode_link_state(ls)
        if inputs.root not in topo.node_ids:
            raise SweepError(
                f"vantage {inputs.root!r} absent from the LSDB"
            )
        with self._probe.phase(pipeline.HOST_FETCH):
            cands = encode_prefix_candidates(
                inputs.prefix_state, topo, area
            )
        return {
            "topo": topo,
            "cands": cands,
            "pair_links": build_pair_links(topo.links),
            "root": inputs.root,
        }

    def _build_multi_context(self, inputs: SweepInputs) -> dict:
        from openr_tpu.decision.backend import DEGREE_BUCKETS
        from openr_tpu.decision.cand_table import CandidateTable
        from openr_tpu.decision.whatif_api import build_pair_links
        from openr_tpu.ops.csr import bucket_for, encode_multi_area
        from openr_tpu.tracing import pipeline

        with self._probe.phase(pipeline.ENCODE):
            enc = encode_multi_area(
                inputs.area_link_states, inputs.root
            )
        with self._probe.phase(pipeline.HOST_FETCH):
            table = CandidateTable()
            table.full_sync(inputs.prefix_state)
            dv = table.derived(enc)
            link_index = np.stack([t.link_index for t in enc.topos])
            pair_links: Dict = {}
            for ai, t in enumerate(enc.topos):
                for pair, vals in build_pair_links(
                    t.links, area_index=ai
                ).items():
                    pair_links.setdefault(pair, []).extend(vals)
        D = bucket_for(max(enc.max_out_degree(), 1), DEGREE_BUCKETS)
        return {
            "enc": enc,
            "table": table,
            "dv": dv,
            "link_index": link_index,
            "pair_links": pair_links,
            "D": D,
            "root": inputs.root,
        }

    # -- world transforms ---------------------------------------------------

    @staticmethod
    def _world_single_topo(topo, world: World):
        """The world's encoded topology: drained nodes out of transit,
        matched link metrics scaled — derived arrays only, layout
        shared.  The dense in-edge planes are dropped (they embed the
        unscaled weights); the repair-sweep kernels read the edge lists
        directly."""
        if not world.drained_nodes and world.metric is None:
            return topo
        w = topo.w
        overloaded = topo.overloaded
        if world.metric is not None:
            match = metric_matcher(world.metric[0])
            scale_link = np.zeros(max(len(topo.links), 1), bool)
            for li, link in enumerate(topo.links):
                if match(link.n1, link.n2):
                    scale_link[li] = True
            edge_scaled = (topo.link_index >= 0) & scale_link[
                np.clip(topo.link_index, 0, None)
            ]
            w = np.where(
                edge_scaled, topo.w * np.float32(world.metric[1]), topo.w
            ).astype(np.float32)
        if world.drained_nodes:
            overloaded = topo.overloaded.copy()
            for name in world.drained_nodes:
                slot = topo.node_ids.get(name)
                if slot is not None:
                    overloaded[slot] = True
        return dataclasses.replace(
            topo,
            w=w,
            overloaded=overloaded,
            in_src=None,
            in_w=None,
            in_ok=None,
            in_rank=None,
            in_edge_pos=None,
            in_has=None,
        )

    # -- engines -----------------------------------------------------------

    def _device_ctx(self, device_index: Optional[int], pool):
        import contextlib

        import jax

        from openr_tpu.ops import jit_guard

        stack = contextlib.ExitStack()
        if pool is not None and device_index is not None:
            stack.enter_context(
                jax.default_device(pool.device(device_index))
            )
            stack.enter_context(jit_guard.dispatch_device(device_index))
        return stack

    def _engine_for(self, ctx, world: World, device_index: Optional[int]):
        """(LinkFailureSweep, SweepRouteSelector) for one (context
        epoch, world, chip) — LRU-bounded; a rebuilt engine's plan()
        rides the content-hash plan cache, so re-creation after a
        prefix-churn context rebuild never replans."""
        key = (self._ctx_epoch, world.key(), device_index)
        hit = self._engines.get(key)
        if hit is not None:
            self._engines.move_to_end(key)
            return hit
        from openr_tpu.ops.sweep_select import SweepRouteSelector
        from openr_tpu.ops.whatif import LinkFailureSweep

        from openr_tpu.tracing import pipeline

        pool = ctx["inputs"].pool
        topo_w = self._world_single_topo(ctx["topo"], world)
        # engine construction is part of the solve budget (base solve +
        # the content-hash-memoized planner pass + selector tables)
        with self._device_ctx(device_index, pool), self._probe.phase(
            pipeline.SWEEP_SHARD_SOLVE, device=device_index
        ):
            sweep = LinkFailureSweep(topo_w, ctx["root"])
            sweep.plan()  # content-hash memoized planner pass
            selector = SweepRouteSelector(
                topo_w, ctx["root"], ctx["cands"], max_degree=sweep.D
            )
        self._engines[key] = (sweep, selector)
        while len(self._engines) > self._engine_cache_entries:
            self._engines.popitem(last=False)
        self.counters.bump("sweep.engine_builds")
        return self._engines[key]

    # -- dispatch / drain ---------------------------------------------------

    def _pick_device(self, pool, exclude=()) -> Optional[int]:
        if pool is None:
            return None
        healthy = [
            i for i in pool.healthy_indices() if i not in exclude
        ]
        if not healthy:
            raise SweepError("no surviving devices to dispatch on")
        dev = healthy[self._rr % len(healthy)]
        self._rr += 1
        return dev

    def _resolve_failures(self, ctx, scenario: Scenario):
        """Scenario link pairs -> the flat failed-link-id set (parallel
        bundles fail whole), or None for an unknown pair (topology
        drifted under the scenario set)."""
        ids: List = []
        for pair in scenario.failed_links:
            hits = ctx["pair_links"].get(frozenset(pair))
            if not hits:
                return None
            ids.extend(hits)
        return tuple(ids)

    def _dispatch_shard(
        self, shard_id: int, dev: Optional[int]
    ) -> _ShardHandle:
        from openr_tpu.tracing import pipeline

        ctx = self._context()
        _sid, lo, hi = self.shards[shard_id]
        scenarios = self.scenarios[lo:hi]
        pool = ctx["inputs"].pool
        groups = []
        # worlds are contiguous within a shard by enumeration order;
        # group defensively anyway
        by_world: "collections.OrderedDict" = collections.OrderedDict()
        for scen in scenarios:
            by_world.setdefault(scen.world.key(), []).append(scen)
        t0 = self.clock.now() if self.clock is not None else 0.0
        # sorted is an identity here (scenarios arrive (world key, hash)-
        # sorted, so insertion order == sorted order) but makes the
        # solve/spill order provably content-derived (orlint
        # unordered-emission)
        for _wkey, items in sorted(by_world.items()):
            world = items[0].world
            fail_sets = []
            errors = []
            for scen in items:
                ids = self._resolve_failures(ctx, scen)
                errors.append(ids is None)
                fail_sets.append(ids if ids is not None else ())
            if ctx["multi"]:
                stats = self._solve_multi(ctx, world, fail_sets, dev)
                groups.append(
                    {
                        "world": world,
                        "items": items,
                        "errors": errors,
                        "pending": None,
                        "stats": stats,
                    }
                )
                continue
            sweep, selector = self._engine_for(ctx, world, dev)
            with self._device_ctx(dev, pool), self._probe.phase(
                pipeline.SWEEP_SHARD_SOLVE, device=dev
            ):
                result = sweep.run_sets(fail_sets, fetch=False)
                pending = selector.start(result)
            if pool is not None and dev is not None:
                pool.note_inflight(dev)
            self.num_device_solves += result.num_device_solves
            self.counters.bump(
                "sweep.device_solves", result.num_device_solves
            )
            groups.append(
                {
                    "world": world,
                    "items": items,
                    "errors": errors,
                    "pending": pending,
                }
            )
        self.counters.bump("sweep.shards_dispatched")
        return _ShardHandle(shard_id, groups, dev, t0)

    def drain_ready(self, handle: _ShardHandle) -> bool:
        return all(
            g["pending"] is None or g["pending"].is_ready()
            for g in handle.groups
        )

    def _drain_shard(self, handle: _ShardHandle) -> List[dict]:
        from openr_tpu.tracing import pipeline

        rows: List[dict] = []
        pool = self._ctx["inputs"].pool if self._ctx else None
        single_groups = 0
        for g in handle.groups:
            if g["pending"] is not None:
                single_groups += 1
                with self._probe.phase(
                    pipeline.STREAM_DRAIN, device=handle.device_index
                ):
                    deltas = g["pending"].finish()
                if self.delta_consumer is not None:
                    self.delta_consumer(
                        self._ctx, handle.shard_id, g, deltas
                    )
                with self._probe.phase(pipeline.DECODE):
                    rows.extend(
                        self._rows_single(handle.shard_id, g, deltas)
                    )
            else:
                with self._probe.phase(pipeline.DECODE):
                    rows.extend(self._rows_multi(handle.shard_id, g))
        if single_groups and pool is not None and handle.device_index is not None:
            pool.note_complete(handle.device_index)
        if self.clock is not None:
            self.counters.observe(
                "sweep.shard_solve_ms",
                (self.clock.now() - handle.t0) * 1000.0,
            )
        return rows

    # -- row extraction -----------------------------------------------------

    def _rows_single(self, shard_id, group, deltas) -> List[dict]:
        from openr_tpu.sweep.reduce import world_deltas

        stats_of_row: Dict[int, tuple] = {}
        rows = []
        for scen, solve, r, delta in world_deltas(group, deltas):
            if solve == "error":
                rows.append(self._row(shard_id, scen, None, "error"))
                continue
            if solve == "alias":
                rows.append(
                    self._row(shard_id, scen, (0, 0, 0, 0.0), "alias")
                )
                continue
            stats = stats_of_row.get(r)
            if stats is None:
                p_idx, valid, metric, _lanes = delta
                was = deltas.base_valid[p_idx]
                withdrawn = int((~valid & was).sum())
                added = int((valid & ~was).sum())
                both = valid & was
                inc = 0.0
                if both.any():
                    diffs = metric[both] - deltas.base_metric[p_idx[both]]
                    if len(diffs):
                        inc = float(max(float(diffs.max()), 0.0))
                stats = (len(p_idx), withdrawn, added, round(inc, 3))
                stats_of_row[r] = stats
            rows.append(self._row(shard_id, scen, stats, "device"))
        return rows

    def _rows_multi(self, shard_id, group) -> List[dict]:
        rows = []
        stats = group["stats"]
        for k, (scen, is_err) in enumerate(
            zip(group["items"], group["errors"])
        ):
            if is_err:
                rows.append(self._row(shard_id, scen, None, "error"))
            else:
                rows.append(
                    self._row(shard_id, scen, stats[k], "device")
                )
        return rows

    @staticmethod
    def _row(shard_id, scen: Scenario, stats, solve: str) -> dict:
        changed, withdrawn, added, inc = stats or (0, 0, 0, 0.0)
        return {
            "shard": shard_id,
            "hash": scen.hash,
            "world": scen.world.key(),
            "failure": [list(p) for p in scen.failed_links],
            "domains": list(scen.domains),
            "changed": changed,
            "withdrawn": withdrawn,
            "added": added,
            "max_metric_increase": inc,
            "solve": solve,
        }

    # -- the multi-area solve ----------------------------------------------

    def _solve_multi(self, ctx, world: World, fail_sets, dev) -> List[tuple]:
        import jax
        import jax.numpy as jnp

        from openr_tpu.decision.whatif_api import FAILURE_BUCKETS
        from openr_tpu.ops.csr import bucket_for
        from openr_tpu.ops.fleet_tables import whatif_multi_area_tables
        from openr_tpu.ops.jit_guard import call_jit_guarded
        from openr_tpu.tracing import pipeline

        enc, dv = ctx["enc"], ctx["dv"]
        pool = ctx["inputs"].pool
        B = len(fail_sets)
        bucket = bucket_for(
            B + 1, FAILURE_BUCKETS + (max(B + 1, FAILURE_BUCKETS[-1]),)
        )
        smax = max([len(t) for t in fail_sets] or [1]) or 1
        S = bucket_for(smax, (1, 2, 4, 8, 16, 32, max(smax, 32)))
        fa = np.full((bucket, S), -1, np.int32)
        fl = np.full((bucket, S), -1, np.int32)
        for i, tup in enumerate(fail_sets):
            for s, (ai, li) in enumerate(tup):
                fa[i, s], fl[i, s] = ai, li
        w = enc.w
        overloaded = enc.overloaded
        if world.metric is not None:
            match = metric_matcher(world.metric[0])
            w = enc.w.copy()
            for ai, t in enumerate(enc.topos):
                scale_link = np.zeros(max(len(t.links), 1), bool)
                for li, link in enumerate(t.links):
                    if match(link.n1, link.n2):
                        scale_link[li] = True
                edge_scaled = (t.link_index >= 0) & scale_link[
                    np.clip(t.link_index, 0, None)
                ]
                w[ai] = np.where(
                    edge_scaled,
                    enc.w[ai] * np.float32(world.metric[1]),
                    enc.w[ai],
                ).astype(np.float32)
        if world.drained_nodes:
            overloaded = enc.overloaded.copy()
            for ai, t in enumerate(enc.topos):
                for name in world.drained_nodes:
                    slot = t.node_ids.get(name)
                    if slot is not None:
                        overloaded[ai, slot] = True
        kernel_args = dict(
            fail_area=jnp.asarray(fa),
            fail_link=jnp.asarray(fl),
            src=jnp.asarray(enc.src),
            dst=jnp.asarray(enc.dst),
            w=jnp.asarray(w),
            edge_ok=jnp.asarray(enc.edge_ok),
            link_index=jnp.asarray(ctx["link_index"]),
            overloaded=jnp.asarray(overloaded),
            soft=jnp.asarray(enc.soft),
            roots=jnp.asarray(enc.roots),
            cand_area=jnp.asarray(dv.cand_area),
            cand_node=jnp.asarray(dv.cand_node),
            cand_ok=jnp.asarray(dv.cand_ok),
            drain_metric=jnp.asarray(dv.drain_metric),
            path_pref=jnp.asarray(dv.path_pref),
            source_pref=jnp.asarray(dv.source_pref),
            distance=jnp.asarray(dv.distance),
            cand_node_in_area=jnp.asarray(dv.cand_node_in_area),
        )
        with self._device_ctx(dev, pool), self._probe.phase(
            pipeline.SWEEP_SHARD_SOLVE, device=dev
        ):
            if pool is not None and dev is not None:
                d = pool.device(dev)
                kernel_args = {
                    k: jax.device_put(v, d) for k, v in kernel_args.items()
                }
            use, shortest, lanes, valid = jax.device_get(
                call_jit_guarded(
                    whatif_multi_area_tables,
                    max_degree=ctx["D"],
                    per_area_distance=ctx["inputs"].per_area_distance,
                    **kernel_args,
                )
            )
        if pool is not None and dev is not None:
            pool.note_dispatch(dev)
        self.num_device_solves += B
        self.counters.bump("sweep.device_solves", B)
        # merged route view (the multi-area engine's decode, counts only)
        m = np.where(valid, shortest, np.inf)
        m_star = m.min(axis=2)
        at_min = valid & (m == m_star[:, :, None])
        eff_lanes = lanes & at_min[:, :, :, None]
        merged = eff_lanes.sum(axis=(2, 3))
        req = np.max(np.where(use, dv.min_nexthop[None, :, :], 0), axis=2)
        route_ok = valid.any(axis=2) & (merged > 0) & (merged >= req)
        base = B  # the first pad row solves the unperturbed world
        out = []
        for s_i in range(B):
            diff = (route_ok[s_i] != route_ok[base]) | (
                route_ok[s_i]
                & route_ok[base]
                & (
                    (m_star[s_i] != m_star[base])
                    | (eff_lanes[s_i] != eff_lanes[base]).any(axis=(1, 2))
                )
            )
            withdrawn = int((route_ok[base] & ~route_ok[s_i]).sum())
            added = int((~route_ok[base] & route_ok[s_i]).sum())
            both = route_ok[base] & route_ok[s_i]
            inc = 0.0
            if both.any():
                d = m_star[s_i][both] - m_star[base][both]
                d = d[np.isfinite(d)]
                if len(d):
                    inc = float(max(float(d.max()), 0.0))
            out.append(
                (int(diff.sum()), withdrawn, added, round(inc, 3))
            )
        return out

    # -- commit -------------------------------------------------------------

    def _commit_shard(self, handle: _ShardHandle, rows: List[dict]) -> None:
        from openr_tpu.tracing import pipeline

        t0 = self.clock.now() if self.clock is not None else 0.0
        with self._probe.phase(pipeline.SWEEP_REDUCE):
            # ordering invariant: rows durable in the spill BEFORE the
            # checkpoint records the shard (docs/Developer_Guide.md)
            self.spill.spill_rows(rows)
            if self.commit_hook is not None:
                # riders (the protection store) persist their per-shard
                # artifacts under the same order: durable before the
                # checkpoint records the shard, so a crash between the
                # two re-runs the shard and overwrites idempotently
                self.commit_hook(handle.shard_id)
            self.checkpoint.commit_shard(
                handle.shard_id,
                {
                    "rows": len(rows),
                    "lo": self.shards[handle.shard_id][1],
                    "hi": self.shards[handle.shard_id][2],
                },
            )
            self.reducer.feed(rows)
        self.completed.add(handle.shard_id)
        self.counters.bump("sweep.shards_completed")
        self.counters.bump("sweep.scenarios_completed", len(rows))
        self.counters.bump("sweep.rows_spilled", len(rows))
        if self.clock is not None:
            self.counters.observe(
                "sweep.reduce_ms", (self.clock.now() - t0) * 1000.0
            )

    def _note_chip_failure(self, dev: Optional[int], exc: Exception) -> None:
        """A dispatch/drain on chip ``dev`` raised: quarantine it via
        the governor's streamed-failure path (probed recovery) and
        drop per-chip engine state — the re-pack dispatches on the
        survivors only."""
        ctx = self._ctx
        governor = ctx["inputs"].governor if ctx else None
        if governor is not None and dev is not None:
            try:
                governor.record_stream_failure(dev, exc)
            except Exception:  # noqa: BLE001 - never mask the original
                pass
        self.num_repacked_shards += 1
        self.counters.bump("sweep.repacked_shards")
        self._engines.clear()

    def _execute_with_repack(
        self, shard_id: int, exclude: List[int]
    ) -> Tuple[_ShardHandle, List[dict]]:
        """Dispatch + drain one shard, re-packing onto the next
        survivor when its chip fails mid-flight (the lost-shard-only
        re-pack)."""
        while True:
            ctx = self._context()
            pool = ctx["inputs"].pool
            dev = self._pick_device(pool, exclude=exclude)
            try:
                handle = self._dispatch_shard(shard_id, dev)
                rows = self._drain_shard(handle)
                return handle, rows
            except SweepError:
                raise
            except Exception as e:  # noqa: BLE001 - chip failure domain
                self._note_chip_failure(dev, e)
                if pool is None or dev is None:
                    raise SweepError(
                        f"shard {shard_id} failed with no device pool to "
                        f"re-pack on: {type(e).__name__}: {e}"
                    ) from e
                exclude.append(dev)

    # -- the run loop --------------------------------------------------------

    def run(
        self,
        yield_cb: Optional[Callable[[], None]] = None,
        stop_after_shards: Optional[int] = None,
    ) -> dict:
        """Execute every pending shard (streamed: up to ``inflight``
        shards in flight, FIFO commit).  ``yield_cb`` runs between
        shard commits (the service actor awaits the clock there);
        ``stop_after_shards`` commits that many then returns (the
        kill-and-resume tests and the bench's resume proof)."""
        inflight: "collections.deque" = collections.deque()
        committed_now = 0

        def commit(handle: _ShardHandle) -> None:
            nonlocal committed_now
            try:
                rows = self._drain_shard(handle)
            except Exception as e:  # noqa: BLE001 - chip failure domain
                self._note_chip_failure(handle.device_index, e)
                exclude = (
                    [handle.device_index]
                    if handle.device_index is not None
                    else []
                )
                handle, rows = self._execute_with_repack(
                    handle.shard_id, exclude
                )
            self._commit_shard(handle, rows)
            committed_now += 1

        try:
            for shard_id in self.pending_shards():
                if self.cancelled or (
                    stop_after_shards is not None
                    and committed_now + len(inflight) >= stop_after_shards
                ):
                    break
                while len(inflight) >= self.inflight_limit:
                    commit(inflight.popleft())
                    if yield_cb is not None:
                        yield_cb()
                ctx = self._context()
                pool = ctx["inputs"].pool
                dev = self._pick_device(pool)
                try:
                    inflight.append(self._dispatch_shard(shard_id, dev))
                except SweepError:
                    raise
                except Exception as e:  # noqa: BLE001 - chip failure
                    self._note_chip_failure(dev, e)
                    # drain what's safely in flight, then re-pack the
                    # failed shard onto the survivors
                    while inflight:
                        commit(inflight.popleft())
                    exclude = [dev] if dev is not None else []
                    handle, rows = self._execute_with_repack(
                        shard_id, exclude
                    )
                    self._commit_shard(handle, rows)
                    committed_now += 1
                    if yield_cb is not None:
                        yield_cb()
            while inflight:
                if not self.cancelled and (
                    stop_after_shards is None
                    or committed_now < stop_after_shards
                ):
                    commit(inflight.popleft())
                else:
                    # cancelled: drop uncommitted in-flight work (the
                    # checkpoint only ever records committed shards —
                    # exactly what a real kill leaves behind)
                    inflight.popleft()
        finally:
            if self.spill is not None:
                self.spill.seal()
        return self.status()

    # -- observability -------------------------------------------------------

    def status(self) -> dict:
        spill = self.spill.stats() if self.spill is not None else {}
        return {
            "sweep_id": self.sweep_id,
            "set_hash": self.set_hash,
            "scenarios_total": len(self.scenarios),
            "scenarios_completed": self.reducer.scenarios,
            "shards_total": len(self.shards),
            "shards_completed": len(self.completed),
            "resumed_shards": self.resumed_shards,
            "repacked_shards": self.num_repacked_shards,
            "device_solves": self.num_device_solves,
            "cancelled": self.cancelled,
            "generations_observed": len(self.generations_observed),
            "spill": spill,
        }

    def summary(self) -> dict:
        return {
            "sweep_id": self.sweep_id,
            "set_hash": self.set_hash,
            "complete": not self.pending_shards(),
            "summary": self.reducer.summary(),
            "summary_digest": self.reducer.summary_digest(),
        }
