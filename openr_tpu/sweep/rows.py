"""Scenario row model — ONE spelling for what-if result rows.

A what-if answer is a list of per-failure entries (``failures``) plus
answer-level metadata.  Historically the streaming watch plane treated
the WHOLE answer as one opaque row, so any change re-emitted the full
scenario result to every subscriber (ROADMAP PR-13 remnant (a)).  The
sweep plane needs the same decomposition to spill and diff per-scenario
results, so the row model lives here and both consume it:

* ``scenario_rows(result)`` — explode a what-if answer into a keyed row
  map: one row per failure entry (keyed by its link pair / link set)
  plus one ``meta`` row for the answer-level fields;
* ``diff_scenario_rows(old, new)`` — the row differ: (updated keys ->
  row, removed keys);
* ``scenario_row_key(entry)`` — the stable per-entry key.

The streaming tier's what-if feeds emit only the rows this differ
reports changed; capacity dashboards watching a running sweep through
``StreamingService`` therefore receive per-scenario-row deltas instead
of whole-result re-emissions.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

#: key namespace for scenario rows inside a feed's row map (the
#: streaming tier prefixes unicast rows "u" and mpls rows "m")
SCENARIO_ROW = "w"
SCENARIO_META = "wmeta"


def scenario_row_key(entry: dict) -> str:
    """Stable content key for one per-failure entry: the sorted link
    pair (single failures / error rows) or the sorted pair list
    (simultaneous sets)."""
    if "link" in entry:
        return "|".join(sorted(map(str, entry["link"])))
    if "links" in entry:
        return ";".join(
            sorted("|".join(sorted(map(str, p))) for p in entry["links"])
        )
    return "?"


def scenario_rows(result: Any) -> Dict[tuple, Any]:
    """Explode a what-if answer into the keyed row map the differ (and
    the streaming feed base) consumes.  Non-dict or failure-less
    answers collapse to a single meta row, so degraded answers still
    stream coherently."""
    if not isinstance(result, dict):
        return {(SCENARIO_META,): result}
    rows: Dict[tuple, Any] = {}
    meta = {k: v for k, v in result.items() if k != "failures"}
    rows[(SCENARIO_META,)] = meta
    for entry in result.get("failures", []) or []:
        if isinstance(entry, dict):
            rows[(SCENARIO_ROW, scenario_row_key(entry))] = entry
    return rows


def diff_scenario_rows(
    old: Dict[tuple, Any], new: Dict[tuple, Any]
) -> Tuple[Dict[tuple, Any], set]:
    """(updated, removed) between two keyed row maps — the shared row
    differ (streaming publish ticks and sweep status feeds)."""
    updated: Dict[tuple, Any] = {}
    removed: set = set()
    for k, row in new.items():
        if old.get(k) != row:
            updated[k] = row
    for k in old:
        if k not in new:
            removed.add(k)
    return updated, removed
