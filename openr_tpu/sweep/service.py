"""SweepService — the capacity-planning sweep as a daemon actor.

One sweep at a time per node: ``start_sweep`` enumerates the grammar
(config defaults overridden by the request params), prepares or resumes
the checkpointed executor, and runs it on a background fiber that
yields to the loop between shard commits — the daemon keeps serving
routes, queries and watches while a 100k-scenario sweep grinds through
the DevicePool.  ``get_sweep_status`` / ``get_sweep_summary`` read the
live executor; ``cancel_sweep`` stops at the next shard boundary
(committed shards stay durable, so a cancelled sweep resumes exactly
like a killed one).

Surfaces: ctrl verbs ``start_sweep`` / ``get_sweep_status`` /
``get_sweep_summary`` / ``cancel_sweep``; ``breeze sweep
run|status|summary|cancel``; ``sweep.*`` counters and the
``sweep.shard_solve_ms`` / ``sweep.reduce_ms`` histograms on the node
CounterMap, plus the ``pipeline.sweep_shard_solve`` /
``pipeline.sweep_reduce`` phase attribution on the backend's shared
PipelineProbe.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from openr_tpu.common.runtime import Actor, Clock, CounterMap
from openr_tpu.sweep.executor import SweepError, SweepExecutor, SweepInputs
from openr_tpu.sweep.scenario import ScenarioSpec


class SweepService(Actor):
    def __init__(
        self,
        node_name: str,
        clock: Clock,
        config,
        decision,
        counters: Optional[CounterMap] = None,
        tracer=None,
    ) -> None:
        super().__init__("sweep", clock, counters)
        from openr_tpu.tracing import disabled_tracer

        self.node_name = node_name
        self.config = config
        self.decision = decision
        self.tracer = tracer if tracer is not None else disabled_tracer()
        self.executor: Optional[SweepExecutor] = None
        self.state = "idle"  # idle|running|done|failed|cancelled
        self.error = ""
        self._run_task = None
        self.num_sweeps_started = 0
        #: vantage override for the CURRENT sweep (a fleet sub-sweep
        #: must solve from the fleet's vantage, not this node's own)
        self._root_override: str = ""
        #: fleet status provider (FleetSweepCoordinator.attach wires
        #: it); when set, get_sweep_status carries the per-node fleet
        #: assignment rows `breeze sweep status` renders
        self._fleet_status_fn = None
        #: fleet epoch provider (attach_fleet wires it): when a
        #: dispatched ``fleet_epoch`` stamp is older than the current
        #: membership epoch, the sweep is FENCED — rejected before the
        #: executor touches disk, counted, returned (never raised)
        self._fleet_epoch_fn = None
        self.num_sweeps_fenced = 0

    # -- inputs ------------------------------------------------------------

    def _inputs(self) -> SweepInputs:
        kwargs = self.decision.capacity_sweep_inputs()
        if self._root_override:
            kwargs = {**kwargs, "root": self._root_override}
        return SweepInputs(**kwargs)

    def enumeration_pairs(self):
        """The canonically sorted link pairs the grammar enumerates
        over, from this node's live sweep inputs.  Public so the fleet
        coordinator can pre-enumerate the FULL scenario set (for the
        content-derived world assignment) without reaching into the
        service's input plumbing."""
        return SweepExecutor._all_pairs(self._inputs())

    def _spill_dir(self) -> str:
        base = self.config.spill_dir
        if base:
            return base
        # node-scoped default, same discipline as the persistent store:
        # two daemons must never interleave one spill directory
        return f"/tmp/openr_tpu_sweep.{self.node_name}"

    # -- ctrl verbs ---------------------------------------------------------

    def start_sweep(self, params: Optional[dict] = None) -> dict:
        """Prepare (or resume) and launch one sweep.  Raises SweepError
        while another sweep is running, or when the grammar/vantage is
        unusable."""
        if self.state == "running":
            raise SweepError(
                f"sweep {self.executor.sweep_id} is already running"
            )
        params = dict(params or {})
        fleet_epoch = params.pop("fleet_epoch", None)
        if fleet_epoch is not None and self._fleet_epoch_fn is not None:
            current = self._fleet_epoch_fn()
            if int(fleet_epoch) < current:
                # stale-epoch work: the membership composition changed
                # between derivation and dispatch — a coordinator (or a
                # partitioned stale one) acting on an old view.  Reject
                # structurally: no executor, no spill, just a counted
                # refusal the dispatcher re-derives from.
                self.num_sweeps_fenced += 1
                self.counters.bump("fleet.fenced.sweep_rejected")
                return {
                    "node": self.node_name,
                    "state": "fenced",
                    "fenced": True,
                    "dispatch_epoch": int(fleet_epoch),
                    "current_epoch": current,
                }
        self._root_override = str(params.get("root", ""))
        spec = ScenarioSpec.from_params(self.config, params)
        ex = SweepExecutor(
            self._inputs,
            str(params.get("spill_dir") or self._spill_dir()),
            clock=self.clock,
            counters=self.counters,
            shard_scenarios=int(
                params.get("shard_scenarios", self.config.shard_scenarios)
            ),
            segment_rows=self.config.spill_segment_rows,
            top_k=self.config.summary_top_k,
            inflight=self.config.inflight_shards,
        )
        report = ex.prepare(spec, resume=bool(params.get("resume", True)))
        self.executor = ex
        self.state = "running"
        self.error = ""
        self.num_sweeps_started += 1
        self.counters.bump("sweep.sweeps_started")
        self.tracer.instant(
            "sweep.start", None, module="sweep",
            sweep_id=ex.sweep_id, scenarios=len(ex.scenarios),
        )
        self._run_task = self.spawn(self._run(ex), name="sweep.run")
        return {**report, "state": self.state}

    async def _run(self, ex: SweepExecutor) -> None:
        span = self.tracer.start_span(
            "sweep.run", None, module="sweep", sweep_id=ex.sweep_id
        )
        loop_clock = self.clock

        # run() is synchronous compute; the yield callback can't await,
        # so shard boundaries hand control back by running the executor
        # in steps from this fiber instead
        try:
            while not ex.cancelled and ex.pending_shards():
                ex.run(stop_after_shards=1)
                self.touch()
                # a small breather per committed shard: the daemon's
                # other actors (and chaos, in SimClock runs) interleave
                # with a long sweep instead of starving behind it
                await loop_clock.sleep(
                    self.config.inter_shard_pause_s
                )
            self.state = "cancelled" if ex.cancelled else "done"
            if ex.cancelled:
                self.counters.bump("sweep.sweeps_cancelled")
            else:
                self.counters.bump("sweep.sweeps_completed")
        except SweepError as e:
            self.state = "failed"
            self.error = str(e)
            self.counters.bump("sweep.sweeps_failed")
        finally:
            self.tracer.end_span(span, state=self.state)

    def attach_fleet(self, status_fn, epoch_fn=None) -> None:
        """Wire the fleet coordinator's status provider onto this node
        (``None`` detaches): ``get_sweep_status`` then carries a
        ``fleet`` section with the cross-node assignment rows, so
        ``breeze sweep status`` against ANY member shows the whole
        fleet sweep — not just the local node's shards.  ``epoch_fn``
        (the membership epoch read) arms stale-epoch fencing on
        ``start_sweep``: dispatches stamped with an older epoch are
        refused with a ``fenced`` response instead of starting."""
        self._fleet_status_fn = status_fn
        self._fleet_epoch_fn = epoch_fn

    def get_sweep_status(self) -> dict:
        out: Dict[str, Any] = {
            "node": self.node_name,
            "state": self.state,
            "error": self.error,
            "sweeps_started": self.num_sweeps_started,
            "sweeps_fenced": self.num_sweeps_fenced,
        }
        if self.executor is not None:
            out.update(self.executor.status())
        if self._fleet_status_fn is not None:
            out["fleet"] = self._fleet_status_fn()
        return out

    def get_sweep_summary(self) -> dict:
        if self.executor is None:
            return {
                "node": self.node_name,
                "state": self.state,
                "complete": False,
                "summary": None,
            }
        return {
            "node": self.node_name,
            "state": self.state,
            **self.executor.summary(),
        }

    def cancel_sweep(self) -> dict:
        if self.executor is not None and self.state == "running":
            self.executor.cancelled = True
        return {"node": self.node_name, "state": self.state}

    # -- observability -------------------------------------------------------

    def gauges(self) -> Dict[str, float]:
        ex = self.executor
        return {
            "sweep.running": 1.0 if self.state == "running" else 0.0,
            "sweep.scenarios_total": float(
                len(ex.scenarios) if ex is not None else 0
            ),
            "sweep.scenarios_done": float(
                ex.reducer.scenarios if ex is not None else 0
            ),
            "sweep.shards_done": float(
                len(ex.completed) if ex is not None else 0
            ),
            "sweep.sweeps_started": float(self.num_sweeps_started),
        }
